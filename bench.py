"""End-of-round benchmark (driver contract).

Measures BASELINE.md configs on the real chip and prints ONE JSON line to
stdout with the headline metric:

    BERT-base MLM training throughput, tokens/sec/chip (BASELINE config 3,
    the north-star metric), on whatever single accelerator is visible.

Diagnostics (LeNet eager step rate, ResNet-50 img/s, MFU breakdown) go to
stderr so stdout stays a single JSON line.

`vs_baseline`: the reference (lijiaqi0612/Paddle) publishes no in-repo
numbers (BASELINE.md: "published": {}), so CUDA parity is proxied by model
FLOPs utilization: strong fused-kernel CUDA BERT pretraining implementations
sit at ~40% MFU. vs_baseline = our_MFU / 0.40 — >= 1.0 means we match or
beat a well-tuned CUDA baseline chip-for-chip.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


CUDA_PARITY_MFU = 0.40


def device_peak_flops() -> float:
    """Peak dense FLOP/s — the per-chip table lives in
    paddle_tpu.cost_model (one source of truth with TrainStep's MFU
    gauge)."""
    from paddle_tpu.cost_model import device_peak_flops as peak
    v = peak()
    if v is None:
        import jax
        log(f"unknown device kind {jax.devices()[0].device_kind!r}; "
            "assuming 100 TFLOP/s")
        return 100e12
    return v


def step_program(step) -> dict:
    """The 'step' program's cost/memory attribution from
    TrainStep.stats() — flops/bytes from lowered.cost_analysis(), the
    peak-HBM estimate from compiled.memory_analysis(). Empty dict when
    the backend publishes no cost model (MFU then falls back to the
    per-model analytic FLOP formulas)."""
    try:
        return dict(step.stats().get("programs", {}).get("step") or {})
    except Exception as e:
        log(f"cost attribution unavailable: {e!r}")
        return {}


def attributed_mfu(step, dt_s: float, fallback_flops_step: float) -> float:
    """MFU from the compiler's own FLOP count for the executed step
    (replaces the hand-maintained per-model constants; the analytic
    formula remains only as the no-cost-model fallback)."""
    prog = step_program(step)
    flops = float(prog.get("flops") or 0.0)
    src = "cost_analysis"
    if not flops:
        flops, src = float(fallback_flops_step), "analytic-fallback"
    mfu = flops / dt_s / device_peak_flops()
    log(f"mfu source: {src} ({flops:.3e} FLOPs/step)")
    return mfu


def peak_hbm_line(name: str, step) -> dict | None:
    """Gated ``<model>_peak_hbm_bytes`` metric line (compare_common-safe:
    absent from old records it simply isn't gated; bytes count as
    lower-is-better in check_bench)."""
    peak = step_program(step).get("peak_hbm_bytes") or 0
    if not peak:
        return None
    log(f"{name}: static peak-HBM estimate {peak / 2**30:.2f} GiB "
        "(train step executable)")
    return metric_line(f"{name}_peak_hbm_bytes", peak, "bytes",
                       vs_baseline=1.0)


def steady_ms(call, iters: int, repeats: int = 3) -> float:
    """Tail-corrected min-of-k steady-state ms per call.

    Two artifacts to defeat on the dev tunnel:
    - multi-ms noise spikes (a single timed loop drifted +23% between
      identical runs, r3→r4 LeNet) → take the MIN over `repeats`
      independent loops (noise only ever adds time; reference gate
      analogue: tools/check_op_benchmark_result.py repeated-run stats);
    - a FIXED ~120 ms final-readback RTT per timed loop (the `float()`
      sync), which inflates short loops by T/iters — measured on BERT:
      172.2/160.0/152.7/149.1 ms/step at iters=5/10/20/40, an exact
      true + T/N fit with T≈122 ms. Production training has no per-step
      host sync, so the tail is a tunnel fixture, not model time.

    Two estimators were tried: the 2-point extrapolation
    (2*t(2N) - t(N)) cancels the tail exactly but DOUBLES sensitivity to
    a noise spike in the long loop (one spiked BERT run came out 2x
    wrong across reruns). The shipped estimator is the low-variance one:
    a single LARGE loop per repeat (callers pass iters~40, so the tail
    is a <=3% conservative bias), min over repeats.
    """
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = call()
        _block(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


def _block(out) -> float:
    """Force completion through the tunnel with a scalar readback."""
    if isinstance(out, (tuple, list)):
        out = out[0]
    return float(out._data if hasattr(out, "_data") else out)


def metric_line(metric: str, value: float, unit: str, vs_baseline: float,
                **extra) -> dict:
    d = {"metric": metric, "value": round(float(value), 3), "unit": unit,
         "vs_baseline": round(float(vs_baseline), 3)}
    d.update({k: round(float(v), 4) for k, v in extra.items()})
    return d


def bench_bert_mlm() -> dict:
    """BERT-base MLM jitted train step; returns tokens/sec + MFU."""
    import paddle_tpu as paddle
    # bf16 MXU passes with f32 accumulation — the production policy the
    # MFU math (bf16 peak) assumes; the framework-wide default is
    # "highest" (full f32) for numerics-sensitive eager work
    paddle.set_flags({"tpu_matmul_precision": "default"})
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM
    from paddle_tpu.optimizer import AdamW

    B, S, M = 48, 512, 76          # batch, seq, masked positions (15%)
    # (v5e sweep under AMP O1 + flash v2: B=48 160.4k tok/s > B=96 155k
    # > B=64 152.7k > B=128 142.7k)
    cfg = BertConfig()             # base: L12 H768 A12 vocab 30528
    paddle.seed(42)
    model = BertForMaskedLM(cfg)

    def loss_fn(layer, ids, pos, labels):
        # AMP O1: bf16 activations through matmul-class ops, f32 master
        # params/optimizer — the reference's mixed-precision pretraining
        # recipe (BASELINE config 5 calls for AMP explicitly)
        with paddle.amp.auto_cast(level="O1"):
            scores = layer(ids, masked_positions=pos)
            return layer.loss(scores, labels)

    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                weight_decay=0.01)
    step = TrainStep(model, loss_fn, opt)

    # End-to-end from raw strings: a synthetic wordpiece vocab + corpus
    # through text.FasterTokenizer (host-side; batches are fixed-shape so
    # the timed loop below measures the same compiled step)
    from paddle_tpu.text import FasterTokenizer
    rng = np.random.default_rng(0)
    words = [f"w{i:05d}" for i in range((cfg.vocab_size - 5) // 2)]
    vocab = {t: i for i, t in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + words
        + ["##" + w for w in words[:cfg.vocab_size - 5 - len(words)]])}
    tok = FasterTokenizer(vocab)
    sentences = [" ".join(rng.choice(words, S + 16)) for _ in range(B)]
    batch = tok(sentences, max_seq_len=S)
    ids = batch["input_ids"]
    log(f"bert: input ids from FasterTokenizer over {B} raw sentences")
    pos = np.stack([rng.choice(S, M, replace=False) for _ in range(B)]
                   ).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, M)).astype(np.int32)

    t0 = time.perf_counter()
    loss = step(ids, pos, labels)
    float(loss)                      # block: compile + first step
    compile_s = time.perf_counter() - t0
    log(f"bert: compile+step1 {compile_s:.1f}s loss={float(loss):.3f}")

    for _ in range(3):               # warmup
        loss = step(ids, pos, labels)
    float(loss)

    dt = steady_ms(lambda: step(ids, pos, labels), iters=40,
                   repeats=3) / 1e3
    tokens_per_sec = B * S / dt

    # step-time attribution via the profiler (VERDICT r2 task 6)
    try:
        from paddle_tpu import profiler as prof
        br = prof.profile_train_step(step, (ids, pos, labels), iters=5)
        log(f"bert breakdown: host {br['host_ms']:.2f} ms, dispatch "
            f"{br['dispatch_ms']:.1f} ms, full step {br['step_ms']:.1f} ms"
            f" (warm compile {br['compile_s']:.2f}s)")
    except Exception as e:
        log(f"bert breakdown failed: {e!r}")

    # Fallback FLOPs/token ~= 6*P_matmul + 12*L*h*S (PaLM appendix B) —
    # used only when the backend publishes no cost model; the primary
    # count comes from the compiled step itself via step_program().
    h, L = cfg.hidden_size, cfg.num_layers
    p_block = L * (12 * h * h)                       # qkvo + 2 mlp mats
    p_embed_head = cfg.vocab_size * h                # tied decoder gemm
    flops_token = 6 * (p_block + p_embed_head * M / S) + 12 * L * h * S
    mfu = attributed_mfu(step, dt, flops_token * B * S)
    log(f"bert: {dt*1e3:.1f} ms/step  {tokens_per_sec:,.0f} tok/s  "
        f"MFU={mfu:.3f}")
    return {"tokens_per_sec": tokens_per_sec, "mfu": mfu,
            "ms_per_step": dt * 1e3, "compile_s": compile_s,
            "hbm_line": peak_hbm_line("bert_base_mlm", step)}


def bench_eager_dispatch() -> None:
    """Eager per-op dispatch cost (VERDICT round-1: the vjp-trace per op is
    the eager engine's known hot spot; this tracks it) — diagnostic."""
    try:
        import paddle_tpu as paddle

        x = paddle.to_tensor(np.ones((64, 64), np.float32))
        y_t = paddle.to_tensor(np.ones((64, 64), np.float32))
        x.stop_gradient = False
        y_t.stop_gradient = False
        z = (x * y_t + x).sum()                  # warm jit + tape caches
        float(z)
        n = 200
        # host tape overhead: dispatch-only loop (no readback) — the
        # python-side cost per op (tape node + cached-jit lookup/dispatch);
        # device/tunnel round-trip excluded until the final readback
        t0 = time.perf_counter()
        for _ in range(n):
            z = x * y_t                          # one tape-recorded op
        host_us = (time.perf_counter() - t0) / n * 1e6
        float(z.sum())
        # end-to-end: readback every op — includes device/tunnel RPC
        t0 = time.perf_counter()
        for _ in range(20):
            float((x * y_t).sum())
        e2e_us = (time.perf_counter() - t0) / 20 * 1e6
        log(f"eager dispatch: {host_us:.0f} us/op host tape overhead "
            f"(dispatch-only), {e2e_us:.0f} us/op with per-op readback "
            "(device/tunnel RTT included)")
    except Exception as e:
        log(f"eager dispatch bench failed: {e!r}")


def bench_lenet_eager():
    """Config 1: LeNet eager (dygraph) step rate."""
    try:
        import paddle_tpu as paddle
        from paddle_tpu.nn import functional as F
        from paddle_tpu.optimizer import Momentum
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        model = LeNet()
        opt = Momentum(learning_rate=0.01, parameters=model.parameters())
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(64, 1, 28, 28))
            .astype(np.float32))
        y = paddle.to_tensor(np.zeros((64,), np.int64))

        def one():
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        one()                                        # warm caches
        # eager leg: SHORT loops on purpose — the per-op dispatch stream
        # hits tunnel queue backpressure on long loops (measured: 226
        # ms/step at 10 iters vs 529 at 20), the opposite failure mode of
        # the jitted legs' fixed readback tail. iters=10 matches the
        # r3/r4 methodology for comparability.
        ms = steady_ms(one, iters=10, repeats=3)
        log(f"lenet eager: {ms:.1f} ms/step (B=64, min of 3 runs)")
        # BASELINE config 1's bar is correctness/convergence, not a CUDA
        # number; vs_baseline tracks the repo's own r3 watermark so the
        # gate sees eager-engine drift (r3: 113.3 ms/step on this chip)
        return metric_line("lenet_eager_ms_per_step", ms, "ms",
                           vs_baseline=113.3 / ms)
    except Exception as e:                            # diagnostics must not
        log(f"lenet eager bench failed: {e!r}")       # sink the headline
        return None


def bench_resnet50():
    """Config 2: ResNet-50 jitted img/s.

    AMP O1 + B=256 (v5e sweep: f32 B=64 848 img/s, f32 B=128 1080,
    AMP B=128 1519, AMP B=256 1649 — bf16 activations halve HBM traffic
    and unlock the larger batch)."""
    try:
        import paddle_tpu as paddle
        from paddle_tpu.jit.to_static import TrainStep
        from paddle_tpu.nn import functional as F
        from paddle_tpu.optimizer import Momentum
        from paddle_tpu.vision.models import resnet50

        B = 256
        paddle.seed(0)
        model = resnet50(num_classes=1000)

        def loss_fn(layer, xb, yb):
            with paddle.amp.auto_cast(level="O1"):
                return F.cross_entropy(layer(xb), yb)

        opt = Momentum(learning_rate=0.1, parameters=model.parameters(),
                       momentum=0.9, weight_decay=1e-4)
        step = TrainStep(model, loss_fn, opt)
        rng = np.random.default_rng(0)
        # device-resident batch: measures the train step, not host->device
        # transfer (production overlaps H2D via the DataLoader prefetcher;
        # this dev tunnel's transfer path is not representative)
        import jax.numpy as jnp
        x = jnp.asarray(rng.normal(size=(B, 3, 224, 224))
                        .astype(np.float32))
        y = jnp.asarray(rng.integers(0, 1000, (B,)).astype(np.int32))

        t0 = time.perf_counter()
        float(step(x, y))
        compile_s = time.perf_counter() - t0
        log(f"resnet50: compile+step1 {compile_s:.1f}s")
        for _ in range(3):
            step(x, y)
        float(step(x, y))
        dt = steady_ms(lambda: step(x, y), iters=40, repeats=3) / 1e3
        imgs = B / dt
        # fallback: ResNet-50 fwd ≈ 4.1 GFLOP/img at 224² (fwd+bwd ≈
        # 3×fwd); CUDA parity proxy for convnets is ~0.30 MFU
        # (well-tuned fp16 A100 ResNet sits near 25-35% of dense peak)
        mfu = attributed_mfu(step, dt, B * 3 * 4.1e9)
        log(f"resnet50: {dt*1e3:.1f} ms/step  {imgs:,.0f} img/s "
            f"MFU={mfu:.3f} (B={B}, min of 3 runs)")
        return [metric_line("resnet50_train_imgs_per_sec", imgs, "img/s",
                            vs_baseline=mfu / 0.30, mfu=mfu),
                metric_line("resnet50_compile_step1_s", compile_s, "s",
                            vs_baseline=1.0),
                peak_hbm_line("resnet50", step)]
    except Exception as e:
        log(f"resnet50 bench failed: {e!r}")
        return None


def bench_gpt2_pp_tp() -> None:
    """Config 4 proper: GPT-2 345M over a pp×mp mesh — the SPMD pipeline
    (scan+ppermute stages) composed with tensor parallelism. Runs whenever
    ≥4 devices are visible; on the single-chip bench harness it logs a
    skip (the schedule itself is validated by tests/test_spmd_pipeline.py
    and the driver's dryrun_multichip on a virtual mesh)."""
    try:
        import jax
        n = len(jax.devices())
        if n < 4:
            log(f"gpt2-345M PP+TP: skipped ({n} device(s) visible; needs a "
                "pp×mp mesh of ≥4 chips — dryrun_multichip config A "
                "exercises this path on a virtual mesh)")
            return
        import paddle_tpu as paddle
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed import fleet
        from paddle_tpu.jit.to_static import TrainStep
        from paddle_tpu.models.gpt import (GPTForPretrainingPipe,
                                           GPTPretrainingCriterion,
                                           gpt2_medium)
        from paddle_tpu.optimizer import AdamW

        pp, mp = 2, 2
        dp = n // (pp * mp)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": dp, "pp_degree": pp,
                                   "mp_degree": mp}
        fleet.init(is_collective=True, strategy=strategy)
        mesh = fleet.get_hybrid_communicate_group().mesh

        B, S, M = 8 * dp, 1024, 8
        cfg = gpt2_medium()
        paddle.seed(0)
        model = GPTForPretrainingPipe(cfg, num_microbatches=M)
        model = fleet.distributed_model(model)
        crit = GPTPretrainingCriterion()

        def loss_fn(layer, ids, labels):
            with paddle.amp.auto_cast(level="O1"):
                return crit(layer(ids), labels)

        step = TrainStep(model, loss_fn,
                         AdamW(learning_rate=1e-4, weight_decay=0.01),
                         mesh=mesh, data_spec=P("dp"), zero_axis="dp")
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        t0 = time.perf_counter()
        l0 = float(step(ids, labels))
        log(f"gpt2-345M PP+TP: compile+step1 {time.perf_counter()-t0:.1f}s "
            f"loss={l0:.2f} mesh(dp={dp},pp={pp},mp={mp})")
        for _ in range(2):
            step(ids, labels)
        float(step(ids, labels))
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(ids, labels)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        log(f"gpt2-345M PP+TP: {dt*1e3:.1f} ms/step  {B*S/dt:,.0f} tok/s "
            f"({B*S/dt/n:,.0f} tok/s/chip, B={B}, S={S}, M={M} microbatches)")
    except Exception as e:
        log(f"gpt2-345M PP+TP bench failed: {e!r}")


def gpt_flops_per_token(h=1024, L=24, V=50304, S=1024) -> float:
    """Analytic training FLOPs/token (6P + attention term, PaLM appendix
    B) — the no-cost-model fallback for attributed_mfu."""
    p_block = L * 12 * h * h
    return 6 * (p_block + V * h) + 12 * L * h * S


def bench_gpt2_345m():
    """Config 4: GPT-2 345M causal LM, single chip (AMP O1); the PP+TP
    variant needs multi-chip hardware.

    No activation recompute: with the bf16 activation stream + flash v2
    the B=8/S=1024 activations fit HBM, and the v5e sweep shows recompute
    only loses (B=8 no-remat 35.2k tok/s / 0.37 model-MFU vs B=16 remat
    28.0k); recompute stays for memory-bound multi-chip configs."""
    try:
        import paddle_tpu as paddle
        from paddle_tpu.jit.to_static import TrainStep
        from paddle_tpu.models.gpt import (GPTForPretraining,
                                           GPTPretrainingCriterion,
                                           gpt2_medium)
        from paddle_tpu.optimizer import AdamW

        B, S = 8, 1024
        cfg = gpt2_medium(use_recompute=False)
        paddle.seed(0)
        model = GPTForPretraining(cfg)
        model.train()
        crit = GPTPretrainingCriterion()

        def loss_fn(layer, ids, labels):
            with paddle.amp.auto_cast(level="O1"):
                return crit(layer(ids), labels)

        step = TrainStep(model, loss_fn,
                         AdamW(learning_rate=1e-4,
                               parameters=model.parameters(),
                               weight_decay=0.01))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        t0 = time.perf_counter()
        l0 = float(step(ids, labels))
        compile_s = time.perf_counter() - t0
        log(f"gpt2-345M: compile+step1 {compile_s:.1f}s loss={l0:.2f}")
        for _ in range(2):
            step(ids, labels)
        float(step(ids, labels))
        dt = steady_ms(lambda: step(ids, labels), iters=40,
                       repeats=3) / 1e3
        tok = B * S / dt
        mfu = attributed_mfu(step, dt,
                             gpt_flops_per_token(S=S) * B * S)
        log(f"gpt2-345M: {dt*1e3:.1f} ms/step  {tok:,.0f} tok/s  "
            f"MFU={mfu:.3f} (B={B}, S={S}, AMP O1, min of 3 runs)")
        return [metric_line("gpt2_345m_tokens_per_sec_per_chip", tok,
                            "tokens/s", vs_baseline=mfu / CUDA_PARITY_MFU,
                            mfu=mfu),
                peak_hbm_line("gpt2_345m", step),
                # NOTE: compile+step1 collapses on a warm persistent
                # cache — cross-record gating of *_compile_step1_s is only
                # apples-to-apples between equally-cold runs (the driver
                # benches in fresh containers; see docs/PERF_TRANSFORMER.md)
                metric_line("gpt2_345m_compile_step1_s", compile_s, "s",
                            vs_baseline=1.0, mfu=mfu)]
    except Exception as e:
        log(f"gpt2-345M bench failed: {e!r}")
        return None


def bench_ernie():
    """Config 5 (single-chip leg): ERNIE-base pretraining — MLM + SOP
    heads, AMP O1. The 1.5B hybrid-parallel shape runs in
    dryrun_multichip leg C (needs the v5e-16 mesh); this leg tracks the
    per-chip kernel efficiency of the same model family."""
    try:
        import paddle_tpu as paddle
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.jit.to_static import TrainStep
        from paddle_tpu.models.ernie import ErnieForPretraining, ernie_base
        from paddle_tpu.optimizer import AdamW

        B, S, M = 48, 512, 76
        cfg = ernie_base()
        paddle.seed(0)
        model = ErnieForPretraining(cfg)
        model.train()

        def loss_fn(layer, ids, pos, labels, sop):
            with paddle.amp.auto_cast(level="O1"):
                mlm, sop_sc = layer(ids, masked_positions=pos)
                return layer.loss(mlm, sop_sc, labels, sop)

        step = TrainStep(model, loss_fn,
                         AdamW(learning_rate=1e-4,
                               parameters=model.parameters(),
                               weight_decay=0.01))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        pos = np.stack([rng.choice(S, M, replace=False)
                        for _ in range(B)]).astype(np.int32)
        labels = rng.integers(0, cfg.vocab_size, (B, M)).astype(np.int32)
        sop = rng.integers(0, 2, (B,)).astype(np.int32)

        t0 = time.perf_counter()
        l0 = float(step(ids, pos, labels, sop))
        compile_s = time.perf_counter() - t0
        log(f"ernie-base: compile+step1 {compile_s:.1f}s loss={l0:.2f}")
        for _ in range(3):
            step(ids, pos, labels, sop)
        float(step(ids, pos, labels, sop))
        dt = steady_ms(lambda: step(ids, pos, labels, sop), iters=40,
                       repeats=3) / 1e3
        tok = B * S / dt
        h, L = cfg.hidden_size, cfg.num_layers
        p_block = L * 12 * h * h
        flops_token = (6 * (p_block + cfg.vocab_size * h * M / S)
                       + 12 * L * h * S)
        mfu = attributed_mfu(step, dt, flops_token * B * S)
        log(f"ernie-base: {dt*1e3:.1f} ms/step  {tok:,.0f} tok/s  "
            f"MFU={mfu:.3f} (B={B}, S={S}, AMP O1, min of 3 runs)")
        return [metric_line("ernie_base_pretrain_tokens_per_sec_per_chip",
                            tok, "tokens/s",
                            vs_baseline=mfu / CUDA_PARITY_MFU, mfu=mfu),
                metric_line("ernie_base_compile_step1_s", compile_s, "s",
                            vs_baseline=1.0, mfu=mfu),
                peak_hbm_line("ernie_base", step)]
    except Exception as e:
        log(f"ernie bench failed: {e!r}")
        return None


def bench_serve(quick: bool = False) -> list:
    """``--serve``: GPT-2 345M decode under the synthetic open-loop load
    generator (paddle_tpu.serving, docs/SERVING.md) — the BENCH_serve
    record: serving tokens/s plus p50/p99 per-dispatch decode latency
    and p50 TTFT, gated by tools/check_bench.py like every other metric
    line (ms = lower-is-better, tokens/s = higher-is-better).

    ``--quick`` swaps in gpt_tiny (CPU smoke: same code path, metric
    names carry the model so tiny numbers never gate 345M records)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import (GPTForPretraining, gpt2_medium,
                                       gpt_tiny)
    from paddle_tpu.serving import (LoadSpec, SamplingParams,
                                    ServingConfig, ServingEngine,
                                    run_open_loop)

    paddle.seed(42)
    if quick:
        name, cfg = "gpt_tiny", gpt_tiny()
        serve_cfg = ServingConfig(max_batch_slots=4, block_size=8,
                                  max_context_len=128,
                                  prefill_buckets=(16, 32),
                                  batch_buckets=(1, 2, 4))
        spec = LoadSpec(num_requests=6, rate_rps=8.0,
                        prompt_len_range=(8, 24), max_new_range=(4, 12),
                        vocab_size=cfg.vocab_size, seed=0,
                        sampling=SamplingParams())
    else:
        name, cfg = "gpt2_345m", gpt2_medium()
        serve_cfg = ServingConfig(max_batch_slots=8, block_size=16,
                                  max_context_len=512,
                                  prefill_buckets=(128, 256),
                                  batch_buckets=(1, 2, 4))
        spec = LoadSpec(num_requests=16, rate_rps=2.0,
                        prompt_len_range=(64, 224),
                        max_new_range=(16, 48),
                        vocab_size=cfg.vocab_size, seed=0,
                        sampling=SamplingParams())
    from paddle_tpu.testing import chaos
    model = GPTForPretraining(cfg)
    engine = ServingEngine(model, serve_cfg)
    t0 = time.perf_counter()
    # warm the serving signatures the load mix will hit BEFORE traffic:
    # production keeps executables resident; cold compiles would land in
    # the first requests' TTFT and gate-noise every record
    n_prog = engine.warmup()
    log(f"serve[{name}]: {n_prog} serving programs warm in "
        f"{time.perf_counter() - t0:.1f}s "
        f"(buckets {serve_cfg.prefill_buckets} x "
        f"{serve_cfg.batch_buckets} + decode)")
    summary = run_open_loop(engine, spec)
    log(f"serve[{name}]: {summary['requests_completed']} requests, "
        f"{summary['tokens_generated']} tokens, "
        f"{summary['tokens_per_sec']:.1f} tok/s, "
        f"decode p50 {summary['decode_step_p50_s']*1e3:.1f} ms / "
        f"p99 {summary['decode_step_p99_s']*1e3:.1f} ms, "
        f"ttft p50 {summary['ttft_p50_s']*1e3:.1f} ms, "
        f"mean occupancy {summary['mean_decode_occupancy']:.2f}, "
        f"preemptions {summary['preemptions']}")
    if chaos.active():
        # `bench.py --serve --chaos <spec>` wires the injector through
        # the serving bench (sites serve.*; run_open_loop survives
        # shed/watchdog outcomes and counts them)
        log(f"serve[{name}] chaos fires: {chaos.fired()}")
    avail, shed = serve_resilience_metrics(summary)
    log(f"serve[{name}]: availability {avail:.1f}%, shed rate "
        f"{shed:.1f}% (rejected {summary['requests_rejected']}, "
        f"failed {summary['requests_failed']}, watchdog trips "
        f"{summary['watchdog_trips']})")
    trace_overhead = serve_trace_overhead(engine, spec)
    log(f"serve[{name}]: tracing overhead {trace_overhead:.1f}% "
        "(tokens/s at FLAGS_trace_sample=1.0 vs off, same engine)")
    endpoint_overhead = serve_metrics_endpoint_overhead(engine, spec)
    log(f"serve[{name}]: /metrics endpoint overhead "
        f"{endpoint_overhead:.1f}% (tokens/s with a 1 Hz scraper "
        "attached vs without, same engine)")
    throughput_lines = serve_throughput_features(model, name, serve_cfg,
                                                 quick=quick)
    fleet_lines = serve_fleet_metrics(model, name, serve_cfg,
                                      quick=quick)
    mt_lines = serve_multitenant_metrics(model, name, serve_cfg,
                                         quick=quick)
    swap_lines = serve_lifecycle_metrics(model, name, serve_cfg,
                                         quick=quick)
    return throughput_lines + fleet_lines + mt_lines + swap_lines + [
        metric_line(f"serve_{name}_tokens_per_sec",
                    summary["tokens_per_sec"], "tokens/s",
                    vs_baseline=1.0,
                    occupancy=summary["mean_decode_occupancy"]),
        metric_line(f"serve_{name}_decode_p50_ms",
                    summary["decode_step_p50_s"] * 1e3, "ms",
                    vs_baseline=1.0),
        metric_line(f"serve_{name}_decode_p99_ms",
                    summary["decode_step_p99_s"] * 1e3, "ms",
                    vs_baseline=1.0),
        metric_line(f"serve_{name}_ttft_p50_ms",
                    summary["ttft_p50_s"] * 1e3, "ms", vs_baseline=1.0),
        metric_line("serve_availability_pct", avail, "%",
                    vs_baseline=1.0),
        metric_line("serve_shed_rate", shed, "shed%", vs_baseline=1.0),
        # overhead% gates on ABSOLUTE points in check_bench (healthy
        # baseline ~0, where a relative gate is undefined) — the
        # measured form of the docs' tracing-overhead claim
        metric_line("serve_trace_overhead_pct", trace_overhead,
                    "overhead%", vs_baseline=1.0),
        # same unit/shape as the tracing line: the live telemetry
        # plane's scrape endpoint must stay ~free or the flag matrix's
        # "attach Prometheus to production" advice is fiction
        metric_line("serve_metrics_endpoint_overhead_pct",
                    endpoint_overhead, "overhead%", vs_baseline=1.0),
    ]


def serve_throughput_features(model, name, serve_cfg, quick: bool) -> list:
    """ISSUE 15 legs: the chat-style shared-prefix workload under mmpp
    bursty arrivals, served twice on the SAME seed — once with every
    throughput feature off (the oracle) and once with the radix prefix
    cache + chunked prefill + speculative decoding ON. Records
    ``serve_prefix_hit_pct`` (hit%), ``serve_spec_accept_pct``
    (accept%), ``serve_tokens_per_sec_chip`` and ``serve_ttft_p99_ms``
    from the flags-ON run, and REFUSES to record unless the greedy
    outputs of the two runs are token-identical (the acceptance
    criterion is an oracle pin, not a vibe)."""
    import dataclasses

    import jax
    import numpy as np
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.serving import (LoadSpec, SamplingParams,
                                    ServingEngine, run_open_loop)

    if quick:
        # chat shape: a dominant shared system prompt plus a short
        # user tail — the regime the prefix cache exists for. One
        # warm-cache request per prefix precedes the measured run
        # (production caches are warm; a 8-request cold window would
        # measure tree fill, not serving).
        chat = LoadSpec(num_requests=10, rate_rps=20.0,
                        prompt_len_range=(4, 12), max_new_range=(6, 12),
                        vocab_size=model.cfg.vocab_size, seed=7,
                        sampling=SamplingParams(), arrival="mmpp",
                        burstiness=2.0, shared_prefix_len=32,
                        prefix_pool_size=2, prefix_zipf=1.2)
        chunk = 16
    else:
        chat = LoadSpec(num_requests=24, rate_rps=4.0,
                        prompt_len_range=(16, 64),
                        max_new_range=(16, 48),
                        vocab_size=model.cfg.vocab_size, seed=7,
                        sampling=SamplingParams(), arrival="mmpp",
                        burstiness=2.0, shared_prefix_len=256,
                        prefix_pool_size=4, prefix_zipf=1.2)
        chunk = 128
    # parity prompts: a shared-prefix pair plus a self-repetitive tail
    # (the regime speculation accelerates) — run through BOTH engines
    rng = np.random.default_rng(11)
    pre = rng.integers(0, model.cfg.vocab_size, (32,)).tolist()
    parity_prompts = [pre + rng.integers(0, model.cfg.vocab_size,
                                         (8,)).tolist(),
                      pre + rng.integers(0, model.cfg.vocab_size,
                                         (5,)).tolist(),
                      [3, 4, 5, 3, 4, 5, 3, 4]]

    def phase(flags_on: bool):
        import contextlib
        ctx = []
        if flags_on:
            ctx = [flag_scope("serve_prefix_cache", True),
                   flag_scope("serve_prefill_chunk", chunk),
                   flag_scope("serve_spec_k", 4)]
        with contextlib.ExitStack() as stack:
            for c in ctx:
                stack.enter_context(c)
            eng = ServingEngine(model, dataclasses.replace(serve_cfg))
            eng.warmup()
        outs = [o[-8:].tolist() for o in eng.generate(
            parity_prompts, max_new_tokens=8)]
        # warm the prefix tree the way production is warm: a short
        # burst of the SAME-seed workload (the pool prefixes derive
        # from the seed, so a different seed would warm the WRONG
        # prefixes) before the measured window; the flags-OFF engine
        # runs the same warm requests, so both phases measure
        # identical offered work on a steady-state engine
        run_open_loop(eng, dataclasses.replace(
            chat, num_requests=chat.prefix_pool_size, rate_rps=1e6))
        # measured window: deltas around the chat run, not the
        # engine-cumulative summary (which spans the warm phases)
        tok0 = eng._stats["tokens_generated"]
        n_ttft0 = len(eng._lat["ttft"])
        t0 = time.perf_counter()
        summary = run_open_loop(eng, chat)
        wall = max(time.perf_counter() - t0, 1e-9)
        tps = (eng._stats["tokens_generated"] - tok0) / wall
        ttft = eng._lat["ttft"][n_ttft0:]
        ttft99 = (float(np.percentile(np.asarray(ttft), 99)) * 1e3
                  if ttft else 0.0)
        eng.shutdown()
        return summary, outs, tps, ttft99

    s_off, outs_off, tps_off, ttft99_off = phase(False)
    s_on, outs_on, tps_on, ttft99_on = phase(True)
    if outs_on != outs_off:
        log("serve[chat]: PARITY FAILURE — greedy outputs with the "
            "throughput features ON diverge from the flags-off oracle; "
            "refusing to record the feature legs")
        log(f"  off: {outs_off}\n  on:  {outs_on}")
        return []
    hit = s_on["prefix_hit_pct"] or 0.0
    accept = s_on["spec_accept_pct"] or 0.0
    n_chips = max(1, jax.device_count())
    log(f"serve[chat/{name}]: mmpp shared-prefix workload, features "
        f"ON vs OFF on seed {chat.seed}: tokens/s {tps_off:.1f} -> "
        f"{tps_on:.1f} ({(tps_on / max(tps_off, 1e-9) - 1) * 100:+.1f}%), "
        f"ttft p99 {ttft99_off:.1f} -> {ttft99_on:.1f} ms; prefix hit "
        f"{hit:.1f}% ({s_on['prefix_hit_tokens']} tokens), spec accept "
        f"{accept:.1f}% ({s_on['spec_accepted']}/{s_on['spec_proposed']}"
        f", {s_on['spec_rolled_back']} rolled back), "
        f"{s_on['prefill_chunks']} chunks, greedy outputs token-"
        "identical to the oracle")
    return [
        metric_line("serve_prefix_hit_pct", hit, "hit%",
                    vs_baseline=1.0),
        metric_line("serve_spec_accept_pct", accept, "accept%",
                    vs_baseline=1.0,
                    proposed=s_on["spec_proposed"]),
        metric_line("serve_tokens_per_sec_chip", tps_on / n_chips,
                    "tokens/s", vs_baseline=1.0,
                    vs_flags_off=round(tps_on / max(tps_off, 1e-9), 3)),
        metric_line("serve_ttft_p99_ms", ttft99_on, "ms",
                    vs_baseline=1.0,
                    vs_flags_off_ms=round(ttft99_off, 1)),
    ]


def serve_fleet_metrics(model, name, serve_cfg, quick: bool) -> list:
    """ISSUE 16 legs: the tenanted shared-prefix workload served once by
    a single replica and once by an N-replica fleet behind the
    prefix-affine :class:`~paddle_tpu.serving.FleetRouter`, both on the
    SAME seed. Records ``serve_fleet_tokens_per_sec`` (aggregate, the
    per-host busy-time model), ``serve_fleet_scaling_eff_pct``
    (aggregate vs N x single-replica, weak-scaling points),
    ``serve_fleet_prefix_hit_pct`` (affinity must keep fleet hit%
    within a few points of one engine) and
    ``serve_router_overhead_p99_ms`` (route-decision latency) and
    ``serve_fleet_monitor_overhead_pct`` (ISSUE 18: fleet tokens/s
    with a 1 Hz FleetFederator attached vs without, absolute points,
    clamped at 0) — and
    REFUSES to record unless the fleet's greedy outputs are
    token-identical to a single engine's (router parity is an oracle
    pin, same contract as the feature legs above)."""
    import dataclasses

    import numpy as np
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.serving import (FleetRouter, LoadSpec, RouterConfig,
                                    SamplingParams, ServingEngine,
                                    run_fleet_open_loop)

    n_fleet = 2 if quick else 4
    if quick:
        rep_cfg = dataclasses.replace(serve_cfg)
        # load heavy enough that EACH fleet replica keeps its batch
        # slots occupied (otherwise the leg measures batching occupancy
        # loss, not router scaling), with enough distinct tenants that
        # the affinity keys hash-spread across the ring
        fleet_spec = LoadSpec(num_requests=48, rate_rps=240.0,
                              prompt_len_range=(4, 12),
                              max_new_range=(6, 12),
                              vocab_size=model.cfg.vocab_size, seed=13,
                              sampling=SamplingParams(),
                              shared_prefix_len=16, prefix_pool_size=4,
                              prefix_zipf=1.05, tenants=16)
    else:
        # smaller per-replica footprint than the single-engine bench:
        # four 345M KV pools at max_context 512 would measure the
        # host's allocator, not the router
        rep_cfg = dataclasses.replace(serve_cfg, max_batch_slots=4,
                                      max_context_len=256)
        fleet_spec = LoadSpec(num_requests=48, rate_rps=24.0,
                              prompt_len_range=(16, 64),
                              max_new_range=(8, 24),
                              vocab_size=model.cfg.vocab_size, seed=13,
                              sampling=SamplingParams(),
                              shared_prefix_len=64, prefix_pool_size=4,
                              prefix_zipf=1.05, tenants=16)
    rng = np.random.default_rng(11)
    pre = rng.integers(0, model.cfg.vocab_size, (16,)).tolist()
    parity_prompts = [pre + rng.integers(0, model.cfg.vocab_size,
                                         (6,)).tolist(),
                      pre + rng.integers(0, model.cfg.vocab_size,
                                         (4,)).tolist(),
                      [3, 4, 5, 3, 4, 5, 3, 4]]

    def build_fleet(n):
        # prefix cache ON in every replica (kill-switch flags read at
        # engine init), so fleet hit% measures affinity, not a cold
        # cache
        with flag_scope("serve_prefix_cache", True):
            reps = {}
            for i in range(n):
                eng = ServingEngine(model, dataclasses.replace(rep_cfg))
                eng.warmup()
                reps[f"r{i}"] = eng
            # saturation threshold above the default: the bench drives
            # a deliberate overload burst, and spilling every queued
            # request off its affinity replica would measure p2c, not
            # the prefix-affine design point (p2c has its own tests)
            return FleetRouter(reps, RouterConfig(
                seed=3, saturation_queue_depth=12))

    def phase(n):
        router = build_fleet(n)
        try:
            # measured window FIRST — run_fleet_open_loop's summary is
            # cumulative, and the parity prompts are deliberately
            # affinity-skewed (shared prefix → one replica), which
            # would poison the busy-time scaling accounting. Greedy
            # parity is cache-state-independent, so gating after the
            # measured run checks the same thing.
            summary = run_fleet_open_loop(router, fleet_spec)
            outs = [o[-8:].tolist() for o in router.generate(
                parity_prompts, max_new_tokens=8)]
        finally:
            router.shutdown()
        return summary, outs

    def federated_phase(n):
        # the ISSUE 18 fleet plane attached in its production shape:
        # federator at 1 Hz over the (shared, in-process) registry with
        # its admin plane bound — measured against the bare fleet run
        # above; startup/teardown stay outside the measured window
        from paddle_tpu.monitor.fleet import (FederatorConfig,
                                              FleetFederator,
                                              local_registry_target)
        router = build_fleet(n)
        fed = FleetFederator([local_registry_target()],
                             FederatorConfig(interval_s=1.0),
                             router=router, port=0)
        fed.start()
        try:
            summary = run_fleet_open_loop(router, fleet_spec)
        finally:
            fed.close()
            router.shutdown()
        return summary

    s_one, outs_one = phase(1)
    s_fleet, outs_fleet = phase(n_fleet)
    if outs_fleet != outs_one:
        log("serve[fleet]: PARITY FAILURE — fleet-routed greedy "
            "outputs diverge from the single-engine oracle; refusing "
            "to record the fleet legs")
        log(f"  single: {outs_one}\n  fleet:  {outs_fleet}")
        return []
    single_tps = max(s_one["aggregate_tokens_per_sec"], 1e-9)
    agg = s_fleet["aggregate_tokens_per_sec"]
    eff = 100.0 * agg / (n_fleet * single_tps)
    p99_ms = s_fleet["route_overhead_p99_s"] * 1e3
    s_fed = federated_phase(n_fleet)
    fed_tps = s_fed["aggregate_tokens_per_sec"]
    monitor_overhead = max(0.0, 100.0 * (agg - fed_tps)
                           / max(agg, 1e-9))
    log(f"serve[fleet/{name}]: federator attached at 1 Hz: "
        f"{fed_tps:.1f} tok/s vs {agg:.1f} bare "
        f"({monitor_overhead:.1f}% overhead)")
    log(f"serve[fleet/{name}]: {n_fleet} replicas on seed "
        f"{fleet_spec.seed}: aggregate {agg:.1f} tok/s vs single "
        f"{single_tps:.1f} ({eff:.1f}% weak-scaling eff), fleet "
        f"prefix hit {s_fleet['fleet_prefix_hit_pct']:.1f}% vs single "
        f"{s_one['fleet_prefix_hit_pct']:.1f}%, routed "
        f"{s_fleet['routed_affine']} affine / "
        f"{s_fleet['routed_balanced']} balanced, route p99 "
        f"{p99_ms:.2f} ms, availability "
        f"{s_fleet['availability_pct']:.1f}%, greedy outputs "
        "token-identical to the single-engine oracle")
    return [
        metric_line("serve_fleet_tokens_per_sec", agg, "tokens/s",
                    vs_baseline=1.0, replicas=n_fleet),
        metric_line("serve_fleet_scaling_eff_pct", eff, "weak%",
                    vs_baseline=1.0),
        metric_line("serve_fleet_prefix_hit_pct",
                    s_fleet["fleet_prefix_hit_pct"], "hit%",
                    vs_baseline=1.0,
                    vs_single=round(s_one["fleet_prefix_hit_pct"], 1)),
        metric_line("serve_router_overhead_p99_ms", p99_ms, "ms",
                    vs_baseline=1.0),
        metric_line("serve_fleet_availability_pct",
                    s_fleet["availability_pct"], "%", vs_baseline=1.0),
        # overhead% gates on ABSOLUTE points in check_bench (healthy
        # values hover near 0, so a ratio gate would flap on noise)
        metric_line("serve_fleet_monitor_overhead_pct",
                    monitor_overhead, "overhead%", vs_baseline=1.0,
                    federated_tokens_per_sec=round(fed_tps, 1)),
    ]


def serve_lifecycle_metrics(model, name, serve_cfg, quick: bool) -> list:
    """ISSUE 20 leg: the zero-downtime weight-push drill. A 2-replica
    hot-swap-armed fleet serves the bursty ``mmpp`` arrival shape while
    the live tree is re-pushed through
    :meth:`~paddle_tpu.serving.ServingEngine.swap_weights` THREE times
    (at the quarter points of the offered schedule, every replica each
    time — the identity candidate makes greedy outputs swap-invariant,
    so any lost token is the cutover's fault, not the weights').
    Records ``serve_swap_availability_pct`` (swap%: absolute points,
    higher-is-better in check_bench — it lives at ~100 where a relative
    band would hide a 9-point outage) and REFUSES to record unless all
    3 swaps cut over on every replica, availability held >= 99.9%, and
    the request accounting closed exactly (offered == completed +
    failed + rejected, zero in flight, zero duplicate ids — the
    zero-lost/zero-dup contract from docs/SERVING.md "Model
    lifecycle")."""
    import dataclasses
    import shutil
    import tempfile

    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.distributed import checkpoint as dckpt
    from paddle_tpu.serving import (FleetRouter, LoadSpec, RouterConfig,
                                    SamplingParams, ServerOverloaded,
                                    ServingEngine, build_requests)

    n_reps = 2
    if quick:
        rep_cfg = dataclasses.replace(serve_cfg)
        spec = LoadSpec(num_requests=48, rate_rps=240.0,
                        prompt_len_range=(4, 12), max_new_range=(6, 12),
                        vocab_size=model.cfg.vocab_size, seed=17,
                        sampling=SamplingParams(), arrival="mmpp",
                        burstiness=3.0, mmpp_switch=0.2,
                        shared_prefix_len=16, prefix_pool_size=4,
                        prefix_zipf=1.05, tenants=8)
    else:
        rep_cfg = dataclasses.replace(serve_cfg, max_batch_slots=4,
                                      max_context_len=256)
        spec = LoadSpec(num_requests=48, rate_rps=24.0,
                        prompt_len_range=(16, 64),
                        max_new_range=(8, 24),
                        vocab_size=model.cfg.vocab_size, seed=17,
                        sampling=SamplingParams(), arrival="mmpp",
                        burstiness=3.0, mmpp_switch=0.2,
                        shared_prefix_len=64, prefix_pool_size=4,
                        prefix_zipf=1.05, tenants=8)
    with flag_scope("serve_hot_swap", True):
        reps = {}
        for i in range(n_reps):
            eng = ServingEngine(model, dataclasses.replace(rep_cfg))
            eng.warmup()
            reps[f"r{i}"] = eng
        router = FleetRouter(reps, RouterConfig(
            seed=3, saturation_queue_depth=12))
    push_dir = tempfile.mkdtemp(prefix="bench_swap_")
    schedule = build_requests(spec)
    quarters = [len(schedule) // 4, len(schedule) // 2,
                (3 * len(schedule)) // 4]
    swaps_done = 0
    rejected = 0
    try:
        # the pushed candidate: the live tree itself, re-saved as a
        # committed manifest checkpoint (identity swap — the strongest
        # isolation of cutover mechanics from weight quality)
        dckpt.save(dict(reps["r0"].params), push_dir,
                   asynchronous=False)
        t0 = time.perf_counter()
        i = 0
        while i < len(schedule) or any(
                r.alive and r.engine.scheduler.has_work
                for r in router.replicas.values()):
            now = time.perf_counter() - t0
            while i < len(schedule) and schedule[i][0] <= now:
                try:
                    router.submit(schedule[i][1])
                except ServerOverloaded:
                    rejected += 1
                i += 1
            if swaps_done < len(quarters) and i >= quarters[swaps_done]:
                # live push: every replica, no drain, traffic running
                for rep in router.replicas.values():
                    info = rep.engine.swap_weights(push_dir)
                    if not info.get("pending"):
                        rep.engine.commit_swap()
                swaps_done += 1
            if not router.step_all() and i < len(schedule):
                wait = schedule[i][0] - (time.perf_counter() - t0)
                if wait > 0:
                    time.sleep(min(wait, 0.05))
        epochs = {n: r.engine.metrics_summary()["weights_epoch"]
                  for n, r in router.replicas.items()}
        summary = router.summary()
    finally:
        router.shutdown()
        shutil.rmtree(push_dir, ignore_errors=True)
    avail = summary["availability_pct"]
    lost = (summary["requests_offered"] - summary["requests_completed"]
            - summary["requests_failed"] - summary["requests_rejected"])
    problems = []
    if any(e != len(quarters) for e in epochs.values()):
        problems.append(f"epochs {epochs} != {len(quarters)} everywhere")
    if avail < 99.9:
        problems.append(f"availability {avail:.2f}% < 99.9%")
    if lost or summary["requests_in_flight"]:
        problems.append(f"{lost} lost / "
                        f"{summary['requests_in_flight']} in flight")
    if summary["duplicate_request_ids"]:
        problems.append(f"{summary['duplicate_request_ids']} duplicate "
                        "request ids")
    if problems:
        log(f"serve[lifecycle]: SWAP DRILL FAILURE — {'; '.join(problems)}"
            "; refusing to record the hot-swap leg")
        return []
    log(f"serve[lifecycle/{name}]: {swaps_done} live swaps x {n_reps} "
        f"replicas under mmpp load: availability {avail:.2f}%, "
        f"{summary['requests_completed']} completed / "
        f"{summary['requests_failed']} failed / {rejected} rejected, "
        f"accounting closed (0 lost, 0 dup), final epochs {epochs}")
    return [
        # swap% gates on ABSOLUTE points, drop = regression
        # (check_bench _ABS_POINT_HIGHER_UNITS)
        metric_line("serve_swap_availability_pct", avail, "swap%",
                    vs_baseline=1.0, swaps=swaps_done,
                    replicas=n_reps),
    ]


def serve_multitenant_metrics(model, name, serve_cfg, quick: bool) -> list:
    """ISSUE 17 legs: the multi-tenant LoRA + int8-quantized-KV serving
    shape. One flags-off oracle engine and one multi-tenant engine
    (``FLAGS_serve_kv_quant=int8``, a LoRAManager pool with one adapter
    per tenant/id in the traffic, a per-tenant admission quota) serve
    the SAME seeded tenanted workload. Records
    ``serve_kv_bytes_per_token`` (bytes/token, lower-is-better; refused
    unless int8 lands at or below 0.55x the bf16 full-precision
    footprint) and ``serve_lora_adapters_per_chip`` (adapters,
    higher-is-better; refused unless the multi-tenant decode p99 held
    the fixed budget of 1.5x the oracle's p99) — and REFUSES to record
    anything unless zero-adapter greedy decode under quant is
    token-identical to the flags-off oracle (same contract as the
    feature/fleet legs)."""
    import dataclasses

    import jax
    import numpy as np
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.serving import (LoadSpec, SamplingParams,
                                    ServingEngine, run_open_loop)

    cfg = model.cfg
    n_tenants, per_tenant = (3, 2) if quick else (4, 2)
    rank = 4 if quick else 8
    if quick:
        spec = LoadSpec(num_requests=12, rate_rps=40.0,
                        prompt_len_range=(4, 12), max_new_range=(4, 10),
                        vocab_size=cfg.vocab_size, seed=23,
                        sampling=SamplingParams(), shared_prefix_len=8,
                        prefix_pool_size=2, tenants=n_tenants,
                        adapter_pool=per_tenant)
    else:
        spec = LoadSpec(num_requests=24, rate_rps=6.0,
                        prompt_len_range=(16, 64),
                        max_new_range=(8, 24),
                        vocab_size=cfg.vocab_size, seed=23,
                        sampling=SamplingParams(), shared_prefix_len=32,
                        prefix_pool_size=2, tenants=n_tenants,
                        adapter_pool=per_tenant)
    rng = np.random.default_rng(29)
    parity_prompts = [rng.integers(0, cfg.vocab_size, (n,)).tolist()
                      for n in (9, 6, 12)]

    def phase(multitenant: bool):
        if multitenant:
            eng_cfg = dataclasses.replace(
                serve_cfg, lora_adapters=n_tenants * per_tenant,
                lora_rank=rank,
                tenant_quota=max(2, serve_cfg.max_batch_slots // 2))
            with flag_scope("serve_kv_quant", "int8"):
                eng = ServingEngine(model, eng_cfg)
            # one LoRA adapter per tenant/id the traffic can name,
            # hot-swapped in through the manager (tiny magnitudes: the
            # leg measures serving capacity, not adapter quality)
            wrng = np.random.default_rng(31)
            L, E, r = cfg.num_layers, cfg.hidden_size, rank
            O = 3 * cfg.hidden_size
            for t in range(n_tenants):
                for k in range(per_tenant):
                    eng.lora.load_adapter(
                        f"tenant{t}/adapter{k}",
                        weights=(wrng.standard_normal((L, r, E))
                                 .astype(np.float32) * 1e-3,
                                 wrng.standard_normal((L, r, O))
                                 .astype(np.float32) * 1e-3))
        else:
            eng = ServingEngine(model, dataclasses.replace(serve_cfg))
        eng.warmup()
        # zero-adapter greedy parity probe: base requests on the
        # multi-tenant engine ride the zero adapter (delta exactly 0.0),
        # so only the int8 KV path separates the two engines here
        outs = [o[-8:].tolist() for o in eng.generate(
            parity_prompts, max_new_tokens=8)]
        # the oracle has no LoRA manager, so its copy of the workload
        # drops the adapter ids; adapter_pool draws from a side RNG, so
        # prompts, lengths and arrival times stay byte-identical
        summary = run_open_loop(
            eng, spec if multitenant
            else dataclasses.replace(spec, adapter_pool=0))
        summary["kv_bytes_per_token"] = eng.cache.kv_bytes_per_token()
        eng.shutdown()
        return summary, outs

    s_off, outs_off = phase(False)
    s_mt, outs_mt = phase(True)
    if outs_mt != outs_off:
        log("serve[multitenant]: PARITY FAILURE — zero-adapter greedy "
            "outputs under FLAGS_serve_kv_quant=int8 diverge from the "
            "flags-off oracle; refusing to record the multi-tenant legs")
        log(f"  off: {outs_off}\n  on:  {outs_mt}")
        return []
    lines = []
    n_chips = max(1, jax.device_count())
    # footprint bound vs FULL-PRECISION bf16 pages (the documented
    # acceptance bound, independent of this engine's configured cache
    # dtype): int8 pages + f32 per-(position, head) scales
    bf16_bytes = 2 * cfg.num_layers * cfg.num_heads * \
        (cfg.hidden_size // cfg.num_heads) * 2
    bq, boff = s_mt["kv_bytes_per_token"], s_off["kv_bytes_per_token"]
    log(f"serve[multitenant/{name}]: kv bytes/token {boff} -> {bq} "
        f"({bq / max(boff, 1):.2f}x vs flags-off, "
        f"{bq / max(bf16_bytes, 1):.2f}x vs bf16 full precision)")
    if bq <= 0.55 * bf16_bytes:
        lines.append(metric_line(
            "serve_kv_bytes_per_token", bq, "bytes/token",
            vs_baseline=1.0, flags_off_bytes=boff,
            vs_bf16=round(bq / max(bf16_bytes, 1), 3)))
    else:
        log("serve[multitenant]: int8 KV footprint exceeds 0.55x bf16 "
            "— refusing to record serve_kv_bytes_per_token")
    # adapters-per-chip at a FIXED p99 budget: the count only records
    # while the multi-tenant decode p99 holds 1.5x the oracle's
    p99_off = s_off["decode_step_p99_s"] or 0.0
    p99_mt = s_mt["decode_step_p99_s"] or 0.0
    budget = 1.5 * p99_off
    n_adapters = n_tenants * per_tenant
    log(f"serve[multitenant/{name}]: {n_adapters} adapters over "
        f"{n_tenants} tenants, decode p99 {p99_mt * 1e3:.1f} ms vs "
        f"budget {budget * 1e3:.1f} ms (1.5x oracle), "
        f"{s_mt['requests_completed']}/{spec.num_requests} completed, "
        f"quota deferrals {s_mt.get('quota_deferred', 0)}")
    if p99_off > 0 and p99_mt <= budget:
        lines.append(metric_line(
            "serve_lora_adapters_per_chip", n_adapters / n_chips,
            "adapters", vs_baseline=1.0,
            p99_ms=round(p99_mt * 1e3, 2),
            budget_ms=round(budget * 1e3, 2)))
    else:
        log("serve[multitenant]: decode p99 blew the fixed budget — "
            "refusing to record serve_lora_adapters_per_chip")
    return lines


def serve_trace_overhead(engine, spec) -> float:
    """Measured tokens/s cost of structured tracing at sample rate 1.0
    (every request traced — the worst case; production head-samples at
    FLAGS_trace_sample=0.01): two open-loop phases on the SAME warm
    engine (no recompiles — tracing is host-side only), tracing off
    then on, compared on wall-clock tokens/s. Returns max(0, %slower);
    sub-noise differences clamp to 0."""
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.monitor import trace as trace_mod
    from paddle_tpu.serving import run_open_loop

    def phase(traced: bool) -> float:
        tok0 = engine._stats["tokens_generated"]
        t0 = time.perf_counter()
        if traced:
            with flag_scope("trace", True), \
                    flag_scope("trace_sample", 1.0):
                run_open_loop(engine, spec)
        else:
            run_open_loop(engine, spec)
        dt = max(time.perf_counter() - t0, 1e-9)
        return (engine._stats["tokens_generated"] - tok0) / dt

    tps_off = phase(False)
    tps_on = phase(True)
    trace_mod.get_tracer().reset()     # bench must not hold the ring
    if tps_off <= 0:
        return 0.0
    return max(0.0, 100.0 * (tps_off - tps_on) / tps_off)


def serve_metrics_endpoint_overhead(engine, spec) -> float:
    """Measured tokens/s cost of the live telemetry plane's scrape
    endpoint: two open-loop phases on the SAME warm engine — without a
    server, then with an embedded AdminServer and a 1 Hz ``/metrics``
    scraper attached (the Prometheus-attached production shape,
    docs/OBSERVABILITY.md scrape-interval guidance). Returns
    max(0, %slower); sub-noise differences clamp to 0 (the overhead%
    gate in tools/check_bench.py rides ABSOLUTE points)."""
    import threading
    import urllib.request
    from paddle_tpu.monitor.server import AdminServer
    from paddle_tpu.serving import run_open_loop

    def phase(scraped: bool) -> float:
        # server bind + scraper-thread startup happen OUTSIDE the timed
        # region: the metric is the steady-state cost of being scraped,
        # not the one-time cost of starting the plane
        srv = th = None
        stop = threading.Event()
        if scraped:
            srv = AdminServer(port=0).start()
            url = srv.url + "/metrics"

            def scraper():
                while not stop.is_set():
                    try:
                        with urllib.request.urlopen(url, timeout=2) as r:
                            r.read()
                    except Exception:
                        pass            # the load phase is the subject;
                    stop.wait(1.0)      # a flaky scrape must not abort it

            th = threading.Thread(target=scraper, daemon=True)
            th.start()
        tok0 = engine._stats["tokens_generated"]
        t0 = time.perf_counter()
        try:
            run_open_loop(engine, spec)
        finally:
            dt = max(time.perf_counter() - t0, 1e-9)
            if scraped:
                stop.set()
                th.join(timeout=2.0)
                srv.close()
        return (engine._stats["tokens_generated"] - tok0) / dt

    tps_off = phase(False)
    tps_on = phase(True)
    if tps_off <= 0:
        return 0.0
    return max(0.0, 100.0 * (tps_off - tps_on) / tps_off)


def serve_resilience_metrics(summary: dict) -> tuple:
    """(availability_pct, shed_rate_pct) of an open-loop serving run:
    availability = requests that completed / requests offered; shed rate
    = requests refused or dropped by admission control (client-side
    rejections + policy sheds + queued expiries) / offered. Failed/
    drained requests count against availability but are not "shed" —
    they were admitted."""
    offered = max(int(summary.get("num_requests") or 0), 1)
    completed = int(summary.get("requests_completed") or 0)
    # only QUEUED expiries are shed; an in-flight expiry was admitted
    # and decoded, so it counts against availability alone
    shed = (int(summary.get("requests_rejected") or 0)
            + int(summary.get("requests_shed") or 0)
            + int(summary.get("requests_expired_queued") or 0))
    return 100.0 * completed / offered, 100.0 * shed / offered


def bench_kernels(quick: bool = False) -> list:
    """``--kernels``: kernel-level microbench of the ops.pallas layer
    (docs/PERF_KERNELS.md) — the BENCH_kernels record. Each kernel is
    timed at the DISPATCH level, so the numbers measure whatever path
    production would serve here: the Pallas body on TPU, the XLA
    fallback elsewhere (``kernel_live`` on each line says which; on the
    CPU tunnel the record is an XLA-fallback bandwidth floor the TPU
    run then gates against as a pure improvement). ``kernel_*_ms``
    gates lower-is-better, ``kernel_*_gbps`` (bytes the op must move /
    wall time — the bandwidth-bound figure of merit) higher-is-better.

    ``--quick``: tiny shapes, smoke only, no record."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn import chunked_ce as cce
    from paddle_tpu.ops import pallas as pallas_ops

    rng = np.random.RandomState(0)
    lines = []

    def gbps(nbytes, ms):
        return nbytes / (ms * 1e-3) / 1e9

    # -- fused chunked CE: fwd+bwd over [N, V] logits ----------------------
    N, V = (256, 2048) if quick else (2048, 32768)
    chunk = min(V, 8192)
    logits = jnp.asarray(rng.randn(N, V).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))
    live = float(pallas_ops.kernel_enabled("chunked_ce", note=False))
    step = jax.jit(jax.value_and_grad(
        lambda l: cce.hard_nll(l, labels, chunk=chunk).sum()))
    step(logits)[0].block_until_ready()          # compile outside the clock
    ms = steady_ms(lambda: step(logits)[0], iters=2 if quick else 5)
    # bytes the op must move: logits read fwd + read bwd + dlogits write
    by = 3 * N * V * 4
    log(f"kernels[ce]: [{N}, {V}] fwd+bwd {ms:.1f} ms, "
        f"{gbps(by, ms):.1f} GB/s (live={live:.0f})")
    lines += [
        metric_line("kernel_chunked_ce_ms", ms, "ms", vs_baseline=1.0,
                    kernel_live=live),
        metric_line("kernel_chunked_ce_gbps", gbps(by, ms), "GB/s",
                    vs_baseline=1.0, kernel_live=live),
    ]

    # -- paged flash-decode: one decode step over the paged KV pool --------
    B, H, D, bs, MB = (2, 4, 16, 4, 4) if quick else (8, 16, 64, 16, 32)
    P = B * MB + 1                               # page 0 = scratch
    kp = jnp.asarray(rng.randn(P, bs, H, D).astype(np.float32))
    vp = jnp.asarray(rng.randn(P, bs, H, D).astype(np.float32))
    tbl = jnp.asarray(
        1 + np.arange(B * MB, dtype=np.int32).reshape(B, MB))
    pos = jnp.full((B,), MB * bs - 1, jnp.int32)  # slots fully grown
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32))
    scale = 1.0 / float(np.sqrt(D))
    live = float(pallas_ops.kernel_enabled("paged_decode", note=False))
    if live:
        from paddle_tpu.ops.pallas.paged_decode import paged_decode_attention
        fn = jax.jit(lambda *a: paged_decode_attention(*a, scale=scale))
    else:
        from paddle_tpu.serving.kv_cache import gather_pages

        def _fallback(q_, kp_, vp_, tbl_, pos_):
            gk, gv = gather_pages(kp_, tbl_), gather_pages(vp_, tbl_)
            cols = jnp.arange(gk.shape[1])
            mask = jnp.where(cols[None, :] <= pos_[:, None], 0.0, -1e30)
            s = (jnp.einsum("bhd,bkhd->bhk", q_, gk) * scale
                 + mask[:, None, :])
            pr = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
            return jnp.einsum("bhk,bkhd->bhd", pr, gv).astype(q_.dtype)

        fn = jax.jit(_fallback)
    fn(q, kp, vp, tbl, pos).block_until_ready()
    ms = steady_ms(lambda: fn(q, kp, vp, tbl, pos).ravel()[0],
                   iters=5 if quick else 20)
    # bytes the step must move: every live K/V page read once
    by = 2 * B * MB * bs * H * D * 4
    log(f"kernels[paged_decode]: B={B} ctx={MB * bs} H={H} D={D} "
        f"{ms:.2f} ms, {gbps(by, ms):.1f} GB/s (live={live:.0f})")
    lines += [
        metric_line("kernel_paged_decode_ms", ms, "ms", vs_baseline=1.0,
                    kernel_live=live),
        metric_line("kernel_paged_decode_gbps", gbps(by, ms), "GB/s",
                    vs_baseline=1.0, kernel_live=live),
    ]

    # -- batched LoRA gather-matmul (bgmv): per-slot adapter deltas --------
    B, S, r, E = (4, 1, 4, 64) if quick else (8, 1, 16, 1024)
    O, A = 3 * E, 9                              # row 0 = zero adapter
    x = jnp.asarray(rng.randn(B, S, E).astype(np.float32))
    ap = jnp.asarray(rng.randn(A, r, E).astype(np.float32) * 0.05)
    bp = jnp.asarray(rng.randn(A, r, O).astype(np.float32) * 0.05)
    ids = jnp.asarray(rng.randint(0, A, (B,)).astype(np.int32))
    live = float(pallas_ops.kernel_enabled("bgmv", note=False))
    if live:
        from paddle_tpu.ops.pallas.bgmv import bgmv as _bgmv
    else:
        from paddle_tpu.ops.pallas.bgmv import bgmv_xla as _bgmv
    fnb = jax.jit(_bgmv)
    fnb(x, ap, bp, ids).block_until_ready()
    ms = steady_ms(lambda: fnb(x, ap, bp, ids).ravel()[0],
                   iters=5 if quick else 20)
    # bytes the op must move: x + the B gathered adapter rows + out
    by = (B * S * E + B * r * (E + O) + B * S * O) * 4
    log(f"kernels[bgmv]: B={B} r={r} E={E} O={O} {ms:.3f} ms, "
        f"{gbps(by, ms):.1f} GB/s (live={live:.0f})")
    lines += [
        metric_line("kernel_bgmv_ms", ms, "ms", vs_baseline=1.0,
                    kernel_live=live),
        metric_line("kernel_bgmv_gbps", gbps(by, ms), "GB/s",
                    vs_baseline=1.0, kernel_live=live),
    ]

    # -- int8 quantized matmul vs the f32 gemm -----------------------------
    M, K, Nn = (64, 256, 256) if quick else (512, 2048, 2048)
    x = jnp.asarray(rng.randn(M, K).astype(np.float32))
    w = jnp.asarray((rng.randn(K, Nn) * 0.05).astype(np.float32))
    from paddle_tpu.ops.pallas.quant_matmul import (int8_linear,
                                                    quantize_per_channel)
    w_q, w_s = quantize_per_channel(w)
    live = float(pallas_ops.kernel_enabled("int8_matmul", note=False))
    if live:
        fn8 = jax.jit(lambda a: int8_linear(a, w_q, w_s))
    else:
        # the pre-kernel slim weight-only path: dequantize into the gemm
        fn8 = jax.jit(lambda a: jnp.matmul(
            a, w_q.astype(a.dtype) * w_s.astype(a.dtype)))
    fnf = jax.jit(lambda a: jnp.matmul(a, w))
    fn8(x).block_until_ready()
    fnf(x).block_until_ready()
    ms8 = steady_ms(lambda: fn8(x).ravel()[0], iters=5 if quick else 20)
    msf = steady_ms(lambda: fnf(x).ravel()[0], iters=5 if quick else 20)
    # weight-traffic win: int8 weights + int8 acts + f32 out
    by = M * K + K * Nn + M * Nn * 4
    log(f"kernels[int8_matmul]: [{M}x{K}]@[{K}x{Nn}] int8 {ms8:.2f} ms "
        f"vs f32 {msf:.2f} ms ({msf / ms8:.2f}x, live={live:.0f})")
    lines += [
        metric_line("kernel_int8_matmul_ms", ms8, "ms", vs_baseline=1.0,
                    kernel_live=live, f32_ms=msf),
        metric_line("kernel_int8_matmul_gbps", gbps(by, ms8), "GB/s",
                    vs_baseline=1.0, kernel_live=live),
    ]
    return lines


def bench_moe_dispatch(T: int, D: int, E: int = 8, top_k: int = 2,
                       cf: float = 2.0, tag: str = "",
                       iters: int = 10) -> tuple:
    """MoE dispatch+combine microbench at [T, D], E experts: wall time
    AND compiler-attributed bytes_accessed for BOTH implementations —
    the acceptance evidence that the sort path lowers the dispatch's
    memory traffic vs the einsum oracle (ISSUE 10). Returns
    (metric_lines, sort_ms)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.cost_model import normalize_cost_analysis
    from paddle_tpu.incubate.moe import (einsum_combine, einsum_dispatch,
                                         moe_capacity, sort_combine,
                                         sort_dispatch, topk_routing)

    C = moe_capacity(T, cf, E)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    r = topk_routing(logits, top_k, C)

    def run(mode):
        if mode == "sort":
            fn = lambda a, rr: sort_combine(          # noqa: E731
                sort_dispatch(a, rr, E, C), rr, C)
        else:
            fn = lambda a, rr: einsum_combine(        # noqa: E731
                einsum_dispatch(a, rr, E, C), rr, C)
        jitted = jax.jit(fn)
        lowered = jitted.lower(x, r)
        cost = normalize_cost_analysis(lowered.compile().cost_analysis())
        by = float(cost.get("bytes accessed") or 0.0)
        jitted(x, r).block_until_ready()
        ms = steady_ms(lambda: jitted(x, r).ravel()[0], iters=iters)
        return ms, by

    ms_s, by_s = run("sort")
    ms_e, by_e = run("einsum")
    name = tag or f"{T}x{D}"
    log(f"moe dispatch[{name}]: E={E} k={top_k} C={C} — sort "
        f"{ms_s:.2f} ms / {by_s / 2**20:.1f} MiB accessed vs einsum "
        f"{ms_e:.2f} ms / {by_e / 2**20:.1f} MiB "
        f"({by_e / max(by_s, 1.0):.1f}x less traffic)")
    if by_s and by_e and by_s >= by_e:
        log(f"MOE GATE: sort dispatch bytes_accessed ({by_s:.3e}) did "
            f"NOT improve on einsum ({by_e:.3e}) at E={E} [{name}]")
    lines = [
        metric_line(f"moe_dispatch_sort_ms_{name}", ms_s, "ms",
                    vs_baseline=ms_e / max(ms_s, 1e-9)),
        metric_line(f"moe_dispatch_einsum_ms_{name}", ms_e, "ms",
                    vs_baseline=1.0),
        metric_line(f"moe_dispatch_sort_bytes_{name}", by_s, "bytes",
                    vs_baseline=by_e / max(by_s, 1.0)),
        metric_line(f"moe_dispatch_einsum_bytes_{name}", by_e, "bytes",
                    vs_baseline=1.0),
    ]
    return lines, ms_s


def _bench_moe_gpt(name: str, cfg, B: int, S: int, warm: int, iters: int,
                   repeats: int = 2) -> list:
    """Train-throughput + routing-health record for one MoE GPT config:
    tokens/s/chip from the jitted TrainStep, drop%/balance harvested
    from ONE eager forward's router stats (traced steps cannot publish),
    plus the dispatch microbench at this config's token shape."""
    import paddle_tpu as paddle
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       GPTPretrainingCriterion)
    from paddle_tpu.optimizer import AdamW

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.train()
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels):
        with paddle.amp.auto_cast(level="O1"):
            return crit(layer(ids), labels) + layer.moe_loss()

    step = TrainStep(model, loss_fn,
                     AdamW(learning_rate=1e-4,
                           parameters=model.parameters(),
                           weight_decay=0.01))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    t0 = time.perf_counter()
    l0 = float(step(ids, labels))
    compile_s = time.perf_counter() - t0
    log(f"moe[{name}]: compile+step1 {compile_s:.1f}s loss={l0:.3f} "
        f"(E={cfg.moe_experts}, every={cfg.moe_every}, "
        f"{len(cfg.moe_layer_indices())} MoE layers)")
    for _ in range(warm):
        step(ids, labels)
    float(step(ids, labels))
    dt = steady_ms(lambda: step(ids, labels), iters=iters,
                   repeats=repeats) / 1e3
    tok = B * S / dt

    # routing health from one eager forward (same weights, no jit): the
    # scan side outputs are concrete there, so the per-layer router
    # gauges land in the registry for monitor_report --moe
    from paddle_tpu.core.tensor import no_grad
    model.eval()
    with no_grad():
        model(paddle.to_tensor(ids))
    n_pub = model.gpt.publish_moe_telemetry()
    stats = model.gpt.moe_layer_stats()
    arr = np.asarray(stats._data)          # [L_moe, 5+E]
    drop_pct = 100.0 * float(arr[:, 2].mean())
    balance = 100.0 * float(arr[:, 4].mean())
    entropy = float(arr[:, 3].mean())
    log(f"moe[{name}]: {dt * 1e3:.1f} ms/step {tok:,.0f} tok/s — "
        f"drop {drop_pct:.1f}%, balance {balance:.1f}, entropy "
        f"{entropy:.2f} nats over {n_pub} layers")
    dlines, _ = bench_moe_dispatch(
        B * S, cfg.hidden_size, E=cfg.moe_experts, top_k=cfg.moe_top_k,
        cf=cfg.moe_capacity_factor, tag=name,
        iters=max(2, iters))
    return [
        metric_line(f"moe_{name}_tokens_per_sec_per_chip", tok,
                    "tokens/s", vs_baseline=1.0),
        metric_line(f"moe_{name}_drop_pct", drop_pct, "drop%",
                    vs_baseline=1.0),
        metric_line(f"moe_{name}_balance", balance, "balance",
                    vs_baseline=balance / 100.0, entropy=entropy),
    ] + dlines


def bench_moe(quick: bool = False) -> list:
    """``--moe``: the MoE record (BENCH_moe.json) — sort-vs-einsum
    dispatch microbench (ms + cost-model bytes_accessed at E=8), the
    gpt2-tiny-8E smoke and (full runs) the gpt2-345M-8E record:
    tokens/s/chip, dispatch ms, drop % (lower-is-better absolute
    points), balance (higher-is-better absolute points) — all gated by
    tools/check_bench.py. Routing-health gauges land in the registry
    dump for ``tools/monitor_report.py --moe``."""
    from paddle_tpu.models.gpt import gpt2_medium, gpt_tiny

    lines = []
    tiny = gpt_tiny(num_layers=4, moe_experts=8)
    lines += _bench_moe_gpt("gpt2_tiny_8e", tiny, B=8, S=64,
                            warm=2, iters=5 if quick else 10)
    if quick:
        return lines
    # gpt2-345M-8E: MoE FFN every 2nd layer (the GShard/Switch
    # interleave), 8 experts at ffn_size hidden. On the CPU bench
    # container this is the committed floor record (tiny batch, few
    # iters); the TPU driver round re-records at full shapes.
    cfg = gpt2_medium(moe_experts=8, moe_every=2)
    lines += _bench_moe_gpt("gpt2_345m_8e", cfg, B=2, S=512,
                            warm=1, iters=2, repeats=1)
    return lines


def run_moe_mode(quick: bool) -> None:
    """--moe: emit ONLY the MoE metric lines, dump the registry (router
    gauges for monitor_report --moe) and write/self-gate BENCH_moe.json
    (full runs) — same contract as --serve/--kernels."""
    import os
    metrics = bench_moe(quick=quick)
    for m in metrics:
        print(json.dumps(m), flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        from paddle_tpu.monitor import get_registry
        mpath = os.path.join(here, "BENCH_monitor.jsonl")
        get_registry().dump_jsonl(mpath, extra={"source": "bench_moe"})
        log(f"monitor: registry dumped to {mpath} "
            "(render: python tools/monitor_report.py --moe)")
    except Exception as e:
        log(f"monitor dump skipped: {e!r}")
    if quick:
        log("moe: --quick run, BENCH_moe.json not written")
        return
    write_gated_record("BENCH_moe.json", metrics)


def _recsys_dedup_parity(dim: int = 16, tol: float = 1e-6) -> float:
    """Pin the dedup lookup (fwd + sparse grads) against the naive
    per-id gather oracle (FLAGS_recsys_dedup off): same rows, same
    post-push table state. Returns the max abs diff; raises over
    ``tol`` — a record must never commit on a broken lookup."""
    import numpy as np
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.recsys import ShardedEmbeddingTable

    rng = np.random.default_rng(7)
    ids = rng.integers(0, 64, size=256)          # heavy duplication
    grads = rng.normal(size=(ids.size, dim)).astype(np.float32)
    diffs = []
    states = []
    for dedup in (True, False):
        with flag_scope("recsys_dedup", dedup):
            tab = ShardedEmbeddingTable(64, dim, optimizer="adagrad",
                                        lr=0.1, seed=11)
            rows = tab.pull(ids)
            tab.push(ids, grads)
            states.append((rows, tab.state_dict()))
    (r_d, s_d), (r_n, s_n) = states
    diffs.append(float(np.abs(r_d - r_n).max()))
    diffs.append(float(np.abs(s_d["data"] - s_n["data"]).max()))
    diffs.append(float(np.abs(s_d["g2"] - s_n["g2"]).max()))
    worst = max(diffs)
    if worst > tol:
        raise RuntimeError(
            f"recsys dedup parity broken: max diff {worst:.3e} "
            f"(fwd/data/g2 = {diffs})")
    return worst


def bench_recsys(quick: bool = False) -> list:
    """``--recsys``: the giant-embedding DLRM record (BENCH_recsys.json;
    docs/RECSYS.md) — criteo-synthetic DLRM training through
    hot-tier-exceeding tiered tables (examples/s, embedding GB/s
    touched, dedup ratio, per-tier hit rates) plus the online ranking
    leg (deadline-bounded lookups under the recsys serving engine).
    The dedup lookup is parity-pinned against the naive per-id gather
    before any metric is recorded, and the record refuses to commit
    unless the tier spill/promotion counters are nonzero (the table
    must actually exceed its hot budget)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import recsys
    from paddle_tpu.models.dlrm import DLRM, DLRMConfig
    from paddle_tpu.recsys import (CriteoSynthetic, RecsysEngine,
                                   RecsysRequest, RecsysServingConfig,
                                   TieredEmbeddingTable)

    paddle.seed(42)
    worst = _recsys_dedup_parity()
    log(f"recsys: dedup-vs-naive parity max diff {worst:.2e} "
        "(fwd + sparse grads, adagrad state)")
    if quick:
        name = "dlrm_tiny"
        cfg = DLRMConfig(num_dense=4, num_sparse=4, vocab_sizes=4096,
                         embedding_dim=16, bottom_mlp=(32,),
                         top_mlp=(32,))
        B, steps, hot, host = 256, 10, 96, 512
        serve_requests, K = 12, 32
    else:
        name = "dlrm_criteo_small"
        cfg = DLRMConfig(num_dense=13, num_sparse=8,
                         vocab_sizes=200_000, embedding_dim=32,
                         bottom_mlp=(64, 32), top_mlp=(64, 32))
        B, steps, hot, host = 512, 25, 512, 2048
        serve_requests, K = 32, 64
    # tables sized to EXCEED the hot-tier budget (vocab >> hot_rows) and
    # the host cache (host_rows < touched rows on full runs): training
    # must spill and promote, or the tiering claim is untested
    tables = [TieredEmbeddingTable(v, cfg.embedding_dim, hot_rows=hot,
                                   host_rows=host, admit_after=2,
                                   lr=0.05, seed=f, name=f"slot{f}")
              for f, v in enumerate(cfg.vocab_list())]
    for t in tables:
        recsys.register_table(t.name, t)
    model = DLRM(cfg, tables=tables)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    gen = CriteoSynthetic(num_dense=cfg.num_dense,
                          num_sparse=cfg.num_sparse,
                          vocab_sizes=cfg.vocab_sizes, alpha=1.05,
                          batch_size=B, seed=0)

    def train_step(i):
        dense, ids, labels = gen.batch(i)
        loss = model.loss(dense, ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss)

    first_loss = train_step(0)
    train_step(1)                      # warm the eager op caches
    b0 = sum(t.bytes_pulled + t.bytes_pushed for t in tables)
    t0 = time.perf_counter()
    last_loss = None
    for i in range(2, 2 + steps):
        last_loss = train_step(i)
    dt = max(time.perf_counter() - t0, 1e-9)
    touched = sum(t.bytes_pulled + t.bytes_pushed for t in tables) - b0
    examples_s = B * steps / dt
    mbps = touched / dt / 1e6
    dedup = float(np.mean([t.dedup_ratio for t in tables]))
    agg = {"hbm_hits": 0, "host_hits": 0, "ssd_reads": 0,
           "lazy_inits": 0, "promotions": 0, "demotions": 0}
    for t in tables:
        for k in agg:
            agg[k] += t.stats[k]
        t.publish_tier_metrics()
    total_hits = (agg["hbm_hits"] + agg["host_hits"] + agg["ssd_reads"]
                  + agg["lazy_inits"])
    hbm_pct = 100.0 * agg["hbm_hits"] / max(total_hits, 1)
    host_pct = 100.0 * agg["host_hits"] / max(total_hits, 1)
    if not (agg["promotions"] and agg["demotions"]):
        raise RuntimeError(
            f"recsys: tier spill/promotion counters are zero ({agg}) — "
            "the table did not exceed its hot budget; the tiering leg "
            "measured nothing")
    log(f"recsys[{name}]: {examples_s:.0f} examples/s "
        f"({steps} steps x B={B}, loss {first_loss:.3f} -> "
        f"{last_loss:.3f}), embedding {mbps:.2f} MB/s touched, dedup "
        f"ratio {dedup:.2f}, tier hits hbm {hbm_pct:.1f}% / host "
        f"{host_pct:.1f}% (promotions {agg['promotions']}, demotions "
        f"{agg['demotions']})")

    # online ranking: deadline-bounded lookups through the SAME (now
    # warm) tables under admission control — the serving half
    eng = RecsysEngine(model, RecsysServingConfig(max_batch=4))
    rng = np.random.default_rng(1)
    for _ in range(serve_requests):
        eng.submit(RecsysRequest(
            rng.normal(size=cfg.num_dense).astype(np.float32),
            gen.sample_ids(rng, K), deadline_s=30.0))
    eng.run()
    s = eng.metrics_summary()
    offered = max(s["requests_submitted"] + s["requests_rejected"], 1)
    avail = 100.0 * s["requests_completed"] / offered
    lookup_p99_ms = (s["lookup_p99_s"] or 0.0) * 1e3
    log(f"recsys[serve]: {s['requests_completed']}/{offered} ranked, "
        f"{s['candidates_per_sec']:.0f} candidates/s, lookup p99 "
        f"{lookup_p99_ms:.2f} ms, e2e p99 {(s['e2e_p99_s'] or 0)*1e3:.1f}"
        " ms")
    recsys.publish_table_hbm()
    return [
        metric_line(f"recsys_{name}_examples_per_sec", examples_s,
                    "examples/s", vs_baseline=1.0),
        metric_line(f"recsys_{name}_embedding_mbps", mbps, "MB/s",
                    vs_baseline=1.0),
        metric_line(f"recsys_{name}_dedup_ratio", dedup, "ratio",
                    vs_baseline=1.0),
        # hit% gates on ABSOLUTE points, higher-is-better (check_bench):
        # a tier-hit-rate collapse is a perf cliff even when examples/s
        # survives on a fast host
        metric_line("recsys_tier_hit_hbm_pct", hbm_pct, "hit%",
                    vs_baseline=1.0),
        metric_line("recsys_tier_hit_host_pct", host_pct, "hit%",
                    vs_baseline=1.0),
        metric_line("recsys_serve_candidates_per_sec",
                    s["candidates_per_sec"] or 0.0, "examples/s",
                    vs_baseline=1.0),
        metric_line("recsys_serve_lookup_p99_ms", lookup_p99_ms, "ms",
                    vs_baseline=1.0),
        metric_line("recsys_serve_availability_pct", avail, "%",
                    vs_baseline=1.0),
    ]


def run_recsys_mode(quick: bool) -> None:
    """--recsys: emit ONLY the recsys metric lines, dump the registry
    (tier hit/occupancy gauges for monitor_report --recsys) and
    write/self-gate BENCH_recsys.json (full runs) — the --moe/--serve
    contract."""
    import os
    metrics = bench_recsys(quick=quick)
    for m in metrics:
        print(json.dumps(m), flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        from paddle_tpu.monitor import get_registry
        mpath = os.path.join(here, "BENCH_monitor.jsonl")
        get_registry().dump_jsonl(mpath, extra={"source": "bench_recsys"})
        log(f"monitor: registry dumped to {mpath} "
            "(render: python tools/monitor_report.py --recsys)")
    except Exception as e:
        log(f"monitor dump skipped: {e!r}")
    if quick:
        log("recsys: --quick run, BENCH_recsys.json not written")
        return
    write_gated_record("BENCH_recsys.json", metrics)


def bench_multichip(quick: bool = False) -> list:
    """``--multichip``: the DP×TP×PP record on an 8-device VIRTUAL mesh
    (docs/PARALLELISM.md methodology) — weak-scaling efficiency across
    mesh shapes, plus 1F1B schedule quality (bubble fraction measured
    from the implemented timetable, exposed-comm fraction) and the
    per-op comm_overlap_ms gauges tools/monitor_report.py --comms
    renders. Writes/self-gates BENCH_multichip.json.

    Weak scaling on a virtual mesh: all N device programs share the host
    cores, so the single-device run of the SAME global batch is the
    zero-overhead reference — eff = t_single / t_mesh isolates the
    partitioning + schedule + collective overhead that becomes the
    weak-scaling loss on a real mesh (where t_single(N·B) ≈ N·t(B), the
    textbook T(1,B)/T(N,N·B)). Model is the GPT-2 architecture at test
    scale (gpt_tiny, 8 layers) so records stay comparable across rounds
    on the CPU container; mesh shapes follow ISSUE 9: dp8 (8×1×1),
    dp2×mp2×pp2, mp2×pp4, and the pp-only 1F1B legs XLA:CPU can run the
    real schedule on (pp2/pp4 over a device prefix)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import env as dist_env, fleet
    from paddle_tpu.distributed.meta_parallel.spmd_pipeline import (
        bubble_fraction, pipeline_comm_model, schedule_timetable)
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.gpt import GPTForPretrainingPipe, gpt_tiny
    from paddle_tpu.optimizer import AdamW

    B, S, M = (8, 32, 4) if quick else (16, 64, 4)
    iters = 3 if quick else 10
    cfg = gpt_tiny(num_layers=8)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.float32)

    def run_shape(dp, mp, pp, schedule):
        """Steady ms/step of the full train step (fwd+bwd+AdamW through
        pretraining_loss) on a dp×mp×pp mesh; dp=mp=pp=0 = the
        single-device reference on the same global batch."""
        fleet.reset()
        dist_env.reset()
        if dp:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": dp, "pp_degree": pp,
                                       "mp_degree": mp}
            fleet.init(is_collective=True, strategy=strategy)
            mesh = fleet.get_hybrid_communicate_group().mesh
        else:
            mesh = None
        paddle.seed(7)
        model = GPTForPretrainingPipe(cfg, num_microbatches=M,
                                      schedule=schedule)
        if mesh is not None:
            model = fleet.distributed_model(model)
        opt = AdamW(learning_rate=1e-3, weight_decay=0.01)

        def loss_fn(layer, i, l, m):
            base = layer._layers if hasattr(layer, "_layers") else layer
            return base.pretraining_loss(i, l, m)

        kw = dict(mesh=mesh, data_spec=P("dp")) if mesh is not None else {}
        step = TrainStep(model, loss_fn, opt, **kw)
        args = (Tensor(ids), Tensor(labels), Tensor(mask))
        t0 = time.perf_counter()
        l0 = float(np.asarray(step(*args)._data))
        compile_s = time.perf_counter() - t0
        step(*args)
        ms = steady_ms(lambda: step(*args), iters=iters, repeats=2)
        return ms, compile_s, l0, mesh

    log(f"multichip: gpt2-arch tiny (L={cfg.num_layers}, "
        f"H={cfg.hidden_size}) B={B} S={S} M={M} on "
        f"{len(jax.devices())} virtual devices")
    t_single, c_s, l_single, _ = run_shape(0, 0, 0, None)
    log(f"multichip[single]: {t_single:.1f} ms/step "
        f"(compile {c_s:.1f}s, loss={l_single:.4f})")

    shapes = [
        ("dp8", 8, 1, 1, "fill_drain"),
        ("dp2mp2pp2", 2, 2, 2, "fill_drain"),
        ("mp2pp4", 1, 2, 4, "fill_drain"),
        ("pp2_1f1b", 1, 1, 2, "1f1b"),
        ("pp4_1f1b", 1, 1, 4, "1f1b"),
    ]
    lines, gates = [], []
    reg = None
    try:
        from paddle_tpu.monitor import get_registry
        reg = get_registry()
    except Exception as e:
        log(f"multichip: registry unavailable ({e!r})")

    for name, dp, mp, pp, sched in shapes:
        t_mesh, c_s, l_mesh, mesh = run_shape(dp, mp, pp, sched)
        eff = 100.0 * t_single / t_mesh if t_mesh > 0 else 0.0
        d_loss = abs(l_mesh - l_single)
        log(f"multichip[{name}]: {t_mesh:.1f} ms/step, weak-scaling eff "
            f"{eff:.1f}% (compile {c_s:.1f}s, loss Δ={d_loss:.2e} vs "
            f"single-device)")
        if d_loss > 2e-3 * max(abs(l_single), 1e-6):
            gates.append(f"{name}: loss parity broken "
                         f"(Δ={d_loss:.2e} vs single-device)")
        if eff < 85.0:
            # the ≥85% acceptance bar is the 1F1B pipeline legs; the
            # other shapes are diagnostic (tiny per-device work makes
            # partitioning overhead loom large at test scale) and gate
            # cross-round via check_bench's weak% unit instead
            if "1f1b" in sched:
                gates.append(f"{name}: weak-scaling eff {eff:.1f}% < 85%")
            else:
                log(f"multichip note: {name} below the 85% target "
                    "(diagnostic shape; gated round-over-round only)")
        lines.append(metric_line(f"multichip_weak_scaling_eff_{name}",
                                 eff, "weak%", vs_baseline=eff / 85.0))
        if "1f1b" not in sched or pp < 2:
            continue

        # schedule quality: bubble measured from the IMPLEMENTED
        # timetable predicates (schedule_timetable replays the traced
        # branch conditions) vs the canonical closed form + 5pts
        tt = schedule_timetable("1f1b", pp, M)
        bubble = 100.0 * tt["bubble_fraction"]
        bound = 100.0 * bubble_fraction("1f1b", pp, M) + 5.0
        if bubble > bound:
            gates.append(f"{name}: bubble {bubble:.1f}% > canonical+5pts "
                         f"({bound:.1f}%)")
        exposed_pct = max(0.0, 100.0 - eff)
        log(f"multichip[{name}]: bubble {bubble:.1f}% "
            f"(canonical bound {bound:.1f}%), exposed-comm "
            f"{exposed_pct:.1f}% of step")
        lines.append(metric_line(f"multichip_{name}_bubble_pct", bubble,
                                 "bubble%", vs_baseline=1.0))
        lines.append(metric_line(f"multichip_{name}_exposed_comm_pct",
                                 exposed_pct, "exposed%",
                                 vs_baseline=1.0))

        # per-op overlap gauges (monitor_report --comms): serial = the
        # schedule's per-step ppermute traffic dispatched back-to-back
        # eagerly, exposed = the measured step-time residual vs the
        # single-device reference, overlapped = what XLA's async
        # scheduling hid
        if reg is None:
            continue
        try:
            mb = B // M
            boundary = jnp.zeros((mb, S, cfg.hidden_size), jnp.float32)
            perm = [(i, i + 1) for i in range(pp - 1)]
            pfn = jax.jit(dist_env.shard_map(
                lambda h: jax.lax.ppermute(h, "pp", perm), mesh=mesh,
                in_specs=P(), out_specs=P(), axis_names={"pp"},
                check_vma=False))
            pfn(boundary).block_until_ready()
            one_ms = steady_ms(lambda: pfn(boundary).ravel()[0],
                               iters=iters, repeats=2)
            model_ops = pipeline_comm_model(
                "1f1b", pp, M, int(boundary.nbytes))["ops"]
            serial_ms = one_ms * model_ops / 2.0   # perm pair per slot
            exposed_ms = max(0.0, t_mesh - t_single)
            overlapped_ms = max(0.0, serial_ms - exposed_ms)
            g = reg.gauge(
                "comm_overlap_ms",
                "per-op comm time of a pipelined step: serial = "
                "back-to-back eager dispatch of the schedule's traffic, "
                "exposed = measured step residual, overlapped = hidden "
                "by async scheduling (bench.py --multichip)")
            for phase, v in (("serial", serial_ms),
                             ("exposed", exposed_ms),
                             ("overlapped", overlapped_ms)):
                g.set(v, op="ppermute", mesh=name, schedule="1f1b",
                      phase=phase)
            log(f"multichip[{name}]: ppermute serial {serial_ms:.2f} ms "
                f"vs exposed {exposed_ms:.2f} ms "
                f"({overlapped_ms:.2f} ms hidden)")
        except Exception as e:
            log(f"multichip[{name}]: overlap gauges skipped: {e!r}")

    # -- expert-parallel leg (ISSUE 10): MoE GPT over an ep-only mesh,
    # the only shape whose manual-ep all_to_alls XLA:CPU can compile —
    # weak-scaling eff + the all_to_all overlap gauges ------------------
    try:
        lines += _multichip_moe_ep_leg(B, S, iters, reg)
    except Exception as e:
        log(f"multichip[ep8_moe]: leg failed: {e!r}")
        gates.append(f"ep8_moe: leg failed ({e!r})")

    for gname in gates:
        log("MULTICHIP GATE: " + gname)
    if not gates:
        log("multichip gate ok: all shapes ≥ 85% weak-scaling eff, "
            "1F1B bubble within canonical+5pts, loss parity held")
    return lines


def _multichip_moe_ep_leg(B: int, S: int, iters: int, reg) -> list:
    """The ``ep8_moe`` leg: gpt2-arch tiny with 8 experts in EVERY layer
    (homogeneous MoE stack, scan-over-layers) trained over an ep-only
    8-device mesh — the explicit shard_map + all_to_all expert-parallel
    program. Measures weak-scaling eff vs the SAME model single-device,
    and publishes ``comm_overlap_ms{op=all_to_all}`` gauges: serial =
    the model's per-step all_to_all traffic dispatched back-to-back
    through the EAGER collective (which also lands the measured
    baseline in the comm_latency series the PR 9 relabel created),
    exposed = the step-time residual, overlapped = hidden."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import collective as coll, env as dist_env, fleet
    from paddle_tpu.distributed.spmd import make_mesh
    from paddle_tpu.incubate.moe import MOE_STATS, reset_moe_stats
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       GPTPretrainingCriterion, gpt_tiny)
    from paddle_tpu.optimizer import AdamW

    cfg = gpt_tiny(num_layers=4, moe_experts=8)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, i, l):
        return crit(layer(i), l) + layer.moe_loss()

    def run(mesh):
        fleet.reset()
        dist_env.reset()
        if mesh is not None:
            dist_env.set_mesh(mesh)
        paddle.seed(7)
        model = GPTForPretraining(cfg)
        kw = dict(mesh=mesh, data_spec=P("ep")) if mesh is not None else {}
        step = TrainStep(model, loss_fn,
                         AdamW(learning_rate=1e-3,
                               parameters=model.parameters()), **kw)
        args = (Tensor(ids), Tensor(labels))
        t0 = time.perf_counter()
        l0 = float(np.asarray(step(*args)._data))
        compile_s = time.perf_counter() - t0
        step(*args)
        ms = steady_ms(lambda: step(*args), iters=iters, repeats=2)
        return ms, compile_s, l0

    t_single, c_s, l_single = run(None)
    log(f"multichip[ep8_moe single]: {t_single:.1f} ms/step "
        f"(compile {c_s:.1f}s, loss={l_single:.4f})")
    reset_moe_stats()
    mesh = make_mesh({"ep": 8})
    t_mesh, c_s, l_mesh = run(mesh)
    eff = 100.0 * t_single / t_mesh if t_mesh > 0 else 0.0
    d_loss = abs(l_mesh - l_single)
    log(f"multichip[ep8_moe]: {t_mesh:.1f} ms/step, weak-scaling eff "
        f"{eff:.1f}% (compile {c_s:.1f}s, loss Δ={d_loss:.2e} vs "
        f"single-device — per-shard aux-loss semantics), "
        f"ep_dispatches={MOE_STATS['ep_dispatches']} "
        f"fallbacks={MOE_STATS['fallbacks']}")
    lines = [metric_line("multichip_weak_scaling_eff_ep8_moe", eff,
                         "weak%", vs_baseline=eff / 85.0)]
    exposed_pct = max(0.0, 100.0 - eff)
    lines.append(metric_line("multichip_ep8_moe_exposed_comm_pct",
                             exposed_pct, "exposed%", vs_baseline=1.0))

    # all_to_all overlap gauges: serial = eager all_to_all dispatches of
    # the model's per-step exchange traffic (2 directions x chunks x
    # MoE layers), measured through distributed.alltoall so the
    # comm_latency_seconds{op=all_to_all} baseline series populates too
    from paddle_tpu.incubate.moe import moe_capacity, resolve_a2a_chunks
    n = 8
    E, D = cfg.moe_experts, cfg.hidden_size
    C_loc = moe_capacity(B * S // n, cfg.moe_capacity_factor, E)
    # the ONE chunk-resolution rule _ep_program executes, so the serial
    # baseline counts the exchanges the model really issues
    chunks = resolve_a2a_chunks(C_loc)
    cs = C_loc // chunks
    # one exchange moves [E, cs, D] per shard = stacked [n, n, ...] blocks
    rows = max(1, (E // n) * cs)
    block = jnp.zeros((n, n, rows, D), jnp.float32)
    g = coll.get_group(0)
    coll.alltoall(block, group=g)              # build/warm the wrapper
    one_ms = steady_ms(
        lambda: coll.alltoall(block, group=g)[0].ravel()[0],
        iters=iters, repeats=2)
    # per OPTIMIZER step: 2 forward exchanges per chunk per MoE layer,
    # and the backward re-issues each one (an all_to_all's transpose is
    # an all_to_all) — 4 x chunks x layers total
    a2a_per_step = 4 * chunks * len(cfg.moe_layer_indices())
    serial_ms = one_ms * a2a_per_step
    exposed_ms = max(0.0, t_mesh - t_single)
    overlapped_ms = max(0.0, serial_ms - exposed_ms)
    if reg is not None:
        try:
            gz = reg.gauge(
                "comm_overlap_ms",
                "per-op comm time of a pipelined step: serial = "
                "back-to-back eager dispatch of the schedule's traffic, "
                "exposed = measured step residual, overlapped = hidden "
                "by async scheduling (bench.py --multichip)")
            for phase, v in (("serial", serial_ms),
                             ("exposed", exposed_ms),
                             ("overlapped", overlapped_ms)):
                gz.set(v, op="all_to_all", mesh="ep8_moe", schedule="moe",
                       phase=phase)
        except Exception as e:
            log(f"multichip[ep8_moe]: overlap gauges skipped: {e!r}")
    log(f"multichip[ep8_moe]: all_to_all serial {serial_ms:.2f} ms "
        f"({a2a_per_step} exchanges/step @ {one_ms:.3f} ms eager) vs "
        f"exposed {exposed_ms:.2f} ms ({overlapped_ms:.2f} ms hidden)")
    fleet.reset()
    dist_env.reset()
    return lines


def run_multichip_mode(quick: bool) -> None:
    """--multichip: needs the 8-device virtual CPU mesh; re-exec into a
    correctly-flagged subprocess when this process already initialized a
    different backend (e.g. a single real TPU chip)."""
    import os
    import subprocess

    import jax
    if len(jax.devices()) < 8 or jax.default_backend() != "cpu":
        env = dict(os.environ)
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        env["JAX_PLATFORMS"] = "cpu"
        log("multichip: re-exec on an 8-device virtual CPU mesh")
        rc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--multichip"]
            + (["--quick"] if quick else []), env=env).returncode
        sys.exit(rc)
    metrics = bench_multichip(quick=quick)
    for m in metrics:
        print(json.dumps(m), flush=True)
    try:
        from paddle_tpu.monitor import get_registry
        mpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_monitor.jsonl")
        get_registry().dump_jsonl(mpath, extra={"source": "bench_multichip"})
        log(f"monitor: registry dumped to {mpath} "
            "(render: python tools/monitor_report.py --comms)")
    except Exception as e:
        log(f"monitor dump skipped: {e!r}")
    if quick:
        log("multichip: --quick run, BENCH_multichip.json not written")
        return
    write_gated_record("BENCH_multichip.json", metrics)


def write_gated_record(rec_name: str, metrics: list) -> None:
    """Write/self-gate a standalone bench record (BENCH_serve.json,
    BENCH_kernels.json): gate the fresh metrics against the existing
    record, park it at ``.prev`` — EVEN when the gate errored (corrupt
    record, import error): a regressed or broken run must never silently
    become the next baseline — then write the fresh record."""
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    rec = os.path.join(here, rec_name)
    tag = rec_name.rsplit(".", 1)[0]
    try:
        sys.path.insert(0, os.path.join(here, "tools"))
        import check_bench
        if os.path.exists(rec):
            with open(rec) as f:
                old = check_bench._metric_list(json.load(f))
            for p in check_bench.compare_common(old, metrics):
                log(f"{tag} GATE: " + p)
    except Exception as e:
        log(f"{tag} gate skipped: {e!r}")
    try:
        if os.path.exists(rec):
            os.replace(rec, rec + ".prev")
    except OSError as e:
        log(f"could not park previous record: {e!r}")
    with open(rec, "w") as f:
        json.dump(metrics, f, indent=1)
    log(f"{tag}: record written to {rec} "
        f"(gate: python tools/check_bench.py {rec_name}.prev {rec_name})")


def run_kernels_mode(quick: bool) -> None:
    """--kernels: emit ONLY the kernel metric lines (one JSON per line)
    and write/self-gate the BENCH_kernels.json record (full runs),
    parking the previous record at .prev — same contract as --serve."""
    metrics = bench_kernels(quick=quick)
    for m in metrics:
        print(json.dumps(m), flush=True)
    if quick:
        log("kernels: --quick run, BENCH_kernels.json not written")
        return
    write_gated_record("BENCH_kernels.json", metrics)


def run_serve_mode(quick: bool) -> None:
    """--serve: emit ONLY the serving metric lines (one JSON per line),
    write/self-gate the BENCH_serve.json record (full runs), and dump
    the monitor registry (per-request latency histograms, queue gauges —
    tools/monitor_report.py --serve renders it)."""
    import os
    metrics = bench_serve(quick=quick)
    for m in metrics:
        print(json.dumps(m), flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        from paddle_tpu.monitor import get_registry
        mpath = os.path.join(here, "BENCH_monitor.jsonl")
        get_registry().dump_jsonl(mpath, extra={"source": "bench_serve"})
        log(f"monitor: registry dumped to {mpath} "
            "(render: python tools/monitor_report.py --serve)")
    except Exception as e:
        log(f"monitor dump skipped: {e!r}")
    if quick:
        log("serve: --quick run, BENCH_serve.json not written")
        return
    write_gated_record("BENCH_serve.json", metrics)


def bench_train_goodput(quick: bool) -> list:
    """--train: goodput-ledger + model-health overhead on a small MLP
    TrainStep. Warm step time with the ledger on and health telemetry
    OFF vs ``FLAGS_train_health_every=1`` (per-layer grad/param/update
    side-outputs compiled INTO the step program — the contract is that
    the cost is compiled arithmetic, not extra dispatches), gated as
    absolute points. Also emits the run's ``train_goodput_pct`` under
    the higher-is-better ``goodput%`` unit so a leak of wall-clock into
    a badput bucket trips check_bench even when step time survives."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.monitor import goodput as goodput_mod

    iters = 10 if quick else 40
    paddle.set_flags({"train_goodput": True, "train_health_every": 0})
    paddle.seed(7)
    model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                          nn.Linear(128, 64), nn.ReLU(),
                          nn.Linear(64, 8))
    step = TrainStep(model, lambda l, a, b: F.cross_entropy(l(a), b),
                     paddle.optimizer.Adam(
                         learning_rate=1e-3,
                         parameters=model.parameters()))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 64)).astype(np.float32)
    y = rng.integers(0, 8, (32,)).astype(np.int64)

    float(step(x, y))                    # compile + step 1
    for _ in range(3):
        loss = step(x, y)
    float(loss)
    ms_off = steady_ms(lambda: step(x, y), iters=iters)

    # health at every step is the worst-case telemetry load; production
    # cadence (every-N) can only cost less readback, same program
    paddle.set_flags({"train_health_every": 1})
    float(step(x, y))                    # health program compile
    for _ in range(3):
        loss = step(x, y)
    float(loss)
    ms_on = steady_ms(lambda: step(x, y), iters=iters)
    paddle.set_flags({"train_health_every": 0})

    overhead = max(0.0, (ms_on - ms_off) / ms_off * 100.0)
    led = goodput_mod.active_ledger()
    snap = led.snapshot() if led is not None else {}
    gp = float(snap.get("goodput_pct", 0.0))
    log(f"train: warm step health-off {ms_off:.3f} ms, health-every-1 "
        f"{ms_on:.3f} ms -> overhead {overhead:.1f} points "
        f"(goodput {gp:.1f}% of {snap.get('elapsed_s', 0.0):.1f}s)")
    for b, s in sorted((snap.get("buckets") or {}).items(),
                       key=lambda kv: -kv[1]):
        if s:
            log(f"train:   {b:<20} {s:8.2f}s")
    return [metric_line("train_goodput_pct", gp, "goodput%",
                        vs_baseline=1.0),
            metric_line("train_goodput_overhead_pct", overhead,
                        "overhead%", vs_baseline=1.0,
                        ms_off=ms_off, ms_on=ms_on)]


def run_train_mode(quick: bool) -> None:
    """--train: emit ONLY the goodput metric lines (one JSON per line),
    write/self-gate the BENCH_train.json record (full runs), and dump
    the monitor registry (goodput gauge/badput counters + per-layer
    health gauges — tools/monitor_report.py --goodput renders it) —
    same contract as --serve."""
    import os
    metrics = bench_train_goodput(quick=quick)
    for m in metrics:
        print(json.dumps(m), flush=True)
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        from paddle_tpu.monitor import get_registry
        mpath = os.path.join(here, "BENCH_monitor.jsonl")
        get_registry().dump_jsonl(mpath, extra={"source": "bench_train"})
        log(f"monitor: registry dumped to {mpath} "
            "(render: python tools/monitor_report.py --goodput)")
    except Exception as e:
        log(f"monitor dump skipped: {e!r}")
    if quick:
        log("train: --quick run, BENCH_train.json not written")
        return
    write_gated_record("BENCH_train.json", metrics)


def main() -> None:
    import jax
    # rbg keys: dropout mask generation is ~10x cheaper than threefry on
    # TPU and BERT training draws masks for every layer every step
    jax.config.update("jax_default_prng_impl", "rbg")

    import paddle_tpu as paddle
    # all benches measure the production policy: bf16 MXU, f32 accumulate
    paddle.set_flags({"tpu_matmul_precision": "default"})
    # telemetry on for the whole run: TrainStep step timings + compile/
    # recompile counters land in the monitor registry, dumped as JSONL
    # next to the BENCH_*.json records at the end (registry writes are
    # host-side dict updates — noise floor, not a timed-loop distortion)
    paddle.set_flags({"monitor": True})
    log(f"devices: {jax.devices()}")
    log(f"compilation cache: {jax.config.jax_compilation_cache_dir} "
        "(compile+step1 timings below collapse on warm runs)")
    if "--chaos" in sys.argv:
        # deterministic fault injection for recovery drills: e.g.
        #   bench.py --quick --chaos grad.nonfinite@3
        # (site spec grammar: paddle_tpu/testing/chaos.py; fires land in
        # the flight-recorder recovery timeline)
        from paddle_tpu.core.flags import get_flag
        from paddle_tpu.testing import chaos
        i = sys.argv.index("--chaos")
        spec = sys.argv[i + 1] if i + 1 < len(sys.argv) else ""
        if not spec or spec.startswith("-"):
            sys.exit("--chaos needs a spec: site[@N|:p][*k][,...] — "
                     "sites: " + ", ".join(sorted(chaos.SITES)))
        seed = int(get_flag("chaos_seed"))
        chaos.configure(spec, seed=seed)
        paddle.set_flags({"flight_recorder": True})
        log(f"chaos armed: {spec} (seed={seed}; flight recorder on)")
    full = "--quick" not in sys.argv
    if "--serve" in sys.argv:
        # serving bench is its own record (BENCH_serve): the training
        # metric lines and the last-line-headline contract stay untouched
        run_serve_mode(quick=not full)
        return
    if "--kernels" in sys.argv:
        # kernel microbench is its own record too (BENCH_kernels)
        run_kernels_mode(quick=not full)
        return
    if "--multichip" in sys.argv:
        # DP×TP×PP weak-scaling / schedule-quality record
        # (BENCH_multichip) on the 8-device virtual mesh
        run_multichip_mode(quick=not full)
        return
    if "--moe" in sys.argv:
        # MoE dispatch + gpt-8E record (BENCH_moe)
        run_moe_mode(quick=not full)
        return
    if "--recsys" in sys.argv:
        # giant-embedding DLRM training + online ranking record
        # (BENCH_recsys)
        run_recsys_mode(quick=not full)
        return
    if "--train" in sys.argv:
        # training goodput ledger + model-health overhead record
        # (BENCH_train)
        run_train_mode(quick=not full)
        return
    metrics = []

    def add(result):
        """Benches return one metric line, a list (throughput +
        compile_step1), or None (failed diagnostic leg)."""
        if isinstance(result, list):
            metrics.extend(m for m in result if m is not None)
        elif result is not None:
            metrics.append(result)

    if full:
        bench_eager_dispatch()
        add(bench_lenet_eager())
        add(bench_resnet50())
        add(bench_gpt2_345m())
        bench_gpt2_pp_tp()
        add(bench_ernie())
    r = bench_bert_mlm()
    # compile + HBM lines BEFORE the throughput line: the headline (BERT
    # tokens/s) metric must stay the LAST printed JSON line for
    # last-line parsers
    if r.get("hbm_line"):
        metrics.append(r["hbm_line"])
    metrics.append(metric_line(
        "bert_base_mlm_compile_step1_s", r["compile_s"], "s",
        vs_baseline=1.0, mfu=r["mfu"]))
    metrics.append(metric_line(
        "bert_base_mlm_tokens_per_sec_per_chip", r["tokens_per_sec"],
        "tokens/s", vs_baseline=r["mfu"] / CUDA_PARITY_MFU, mfu=r["mfu"]))
    # one JSON line per BASELINE config; the headline (BERT) line LAST so
    # a last-line parser still sees the north-star metric.
    # tools/check_bench.py gates these against the previous round's record.
    for m in metrics:
        if m is not None:
            print(json.dumps(m), flush=True)

    # metrics-registry dump NEXT TO the BENCH_*.json records: perf numbers
    # now travel with their recompile counts, cache hit rates, step-time
    # histograms and comms counters (tools/monitor_report.py renders it).
    # File output only — stdout keeps its one-JSON-line-per-metric
    # contract, so check_bench.compare_common gating is unaffected.
    try:
        import os as _os
        from paddle_tpu.monitor import get_registry
        from paddle_tpu.monitor.memory import publish_census
        from paddle_tpu.utils.compilation import publish_compile_counts
        publish_compile_counts()
        publish_census()      # live-buffer bytes by category, for the
        # tools/monitor_report.py --memory section
        mpath = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                              "BENCH_monitor.jsonl")
        get_registry().dump_jsonl(mpath, extra={"source": "bench"})
        log(f"monitor: registry dumped to {mpath} "
            "(render: python tools/monitor_report.py)")
    except Exception as e:                       # telemetry must never
        log(f"monitor dump skipped: {e!r}")      # sink the metrics

    # self-gate against the newest driver record so a regression is
    # visible in this run's own log (the CLI gate remains for CI use)
    try:
        import glob
        import os
        recs = sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json")))
        if recs:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import check_bench
            with open(recs[-1]) as f:
                old = check_bench._metric_list(json.load(f))
            # intersection-only: a --quick run (or a failed diagnostic
            # leg) intentionally skips benchmarks — those must not log as
            # "metric disappeared" regressions in the self-gate
            problems = check_bench.compare_common(
                old, [m for m in metrics if m is not None])
            for p in problems:
                log("BENCH GATE vs " + os.path.basename(recs[-1]) + ": "
                    + p)
            if old and not problems:
                log(f"bench gate ok vs {os.path.basename(recs[-1])}: "
                    "no metric regressed beyond 10%")
    except Exception as e:                       # the gate must never sink
        log(f"bench gate skipped: {e!r}")        # the metrics themselves


if __name__ == "__main__":
    main()
