"""Dev tool: attribute BERT step time by timing ablations on the chip."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def timed_step(step, args, iters=15):
    loss = step(*args)
    float(loss)
    for _ in range(3):
        loss = step(*args)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(*args)
    float(loss)
    return (time.perf_counter() - t0) / iters * 1e3


def build(hidden_do=0.1, attn_do=0.1, flash=True, fwd_only=False,
          no_opt=False):
    import paddle_tpu as paddle
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM
    from paddle_tpu.optimizer import AdamW

    import paddle_tpu.ops.attention as att
    if not flash:
        att._flash_supported = lambda *a, **k: False
    else:
        import importlib
        importlib.reload(att)

    B, S, M = 48, 512, 76
    cfg = BertConfig(hidden_dropout_prob=hidden_do,
                     attention_dropout_prob=attn_do)
    paddle.seed(42)
    model = BertForMaskedLM(cfg)

    def loss_fn(layer, ids, pos, labels):
        with paddle.amp.auto_cast(level="O1"):
            scores = layer(ids, masked_positions=pos)
            return layer.loss(scores, labels)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    pos = np.stack([rng.choice(S, M, replace=False) for _ in range(B)]
                   ).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, M)).astype(np.int32)

    if fwd_only:
        import jax
        from paddle_tpu.core.random import trace_rng
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.jit.functional import bind, buffer_arrays, \
            param_arrays
        params = param_arrays(model)
        bufs = buffer_arrays(model)

        @jax.jit
        def fwd(p, i, po, la):
            with trace_rng(jax.random.key(0)):
                with bind(model, p, dict(bufs)):
                    return loss_fn(model, Tensor(i), Tensor(po),
                                   Tensor(la))._data

        return (lambda i, po, la: fwd(params, i, po, la)), (ids, pos, labels)

    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                weight_decay=0.01)
    step = TrainStep(model, loss_fn, opt)
    return step, (ids, pos, labels)


def main():
    import jax
    jax.config.update("jax_default_prng_impl", "rbg")
    import paddle_tpu as paddle
    paddle.set_flags({"tpu_matmul_precision": "default"})
    which = sys.argv[1:] or ["base", "nodrop", "noattndrop", "noflash",
                             "fwdonly", "fwdonly_nodrop"]
    cfgs = {
        "base": dict(),
        "nodrop": dict(hidden_do=0.0, attn_do=0.0),
        "noattndrop": dict(attn_do=0.0),
        "nohiddendrop": dict(hidden_do=0.0),
        "noflash": dict(flash=False),
        "fwdonly": dict(fwd_only=True),
        "fwdonly_nodrop": dict(fwd_only=True, hidden_do=0.0, attn_do=0.0),
    }
    for name in which:
        step, args = build(**cfgs[name])
        ms = timed_step(step, args)
        tok = 48 * 512 / (ms / 1e3)
        log(f"{name:16s} {ms:7.1f} ms/step  {tok:10,.0f} tok/s")


if __name__ == "__main__":
    main()
