"""Benchmark regression gate.

Compares the metric lines of two driver bench records (BENCH_r{N}.json)
and fails loudly when a metric regressed beyond tolerance — the analogue
of the reference's op-benchmark CI gate
(/root/reference/tools/check_op_benchmark_result.py:1, which diffs op
timings against the develop branch and fails the PR over threshold).

Usage:
    python tools/check_bench.py BENCH_r04.json BENCH_r05.json
    python tools/check_bench.py --tolerance 0.15 old.json new.json

Metric direction is derived from the unit: cost-like units (ms, s, us,
bytes — compile time, step time, peak-HBM estimates) regress when they
grow; rate-like units (tokens/s, img/s, steps/s) regress when they
shrink. The default tolerance (10%) absorbs normal tunnel noise;
bench.py's min-of-k timing keeps the noise floor below it.

Exit code: 0 = no regression, 1 = regression(s), 2 = usage/parse error.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional

DEFAULT_TOLERANCE = 0.10
# cost-like units: growth is the regression (memory units gate the
# *_peak_hbm_bytes budget lines the same way time units gate compile/step
# time). bytes/token and bytes/slot are per-unit KV-cache footprints
# (BENCH_serve, serve_kv_bytes_per_token / serve_kv_bytes_per_slot):
# growth means the int8 paged-KV compression (FLAGS_serve_kv_quant)
# regressed toward full precision, so they self-gate like memory.
_TIME_UNITS = {"ms", "s", "us", "ms/step", "seconds", "bytes", "kib",
               "mib", "gib", "bytes/token", "bytes/slot"}
# bounded 0-100 cost rates (growth is the regression) gate on ABSOLUTE
# percentage points: the healthy baseline is 0, where a relative ratio
# is undefined and the v_old==0 skip would otherwise make the metric
# ungateable ("%" alone stays rate-like and relative:
# serve_availability_pct regresses when it shrinks). bubble% is the
# pipeline-schedule idle share (MULTICHIP record); drop% is the MoE
# router's dropped-assignment share (BENCH_moe); overhead% is the
# measured tracing tokens/s cost (BENCH_serve) — same shape, healthy
# baseline ~0.
_ABS_POINT_UNITS = {"shed%", "bubble%", "exposed%", "drop%",
                    "overhead%"}
# bounded 0-100 QUALITY rates (a drop is the regression), also gated on
# absolute points: weak-scaling efficiency sits near 100, where the
# relative 10% band would hide a 9-point efficiency loss; balance is the
# MoE expert-load balance (100 = uniform), gated the same way so
# BENCH_moe trips on routing-health collapse, not just throughput.
# hit% is a recsys tier hit rate (BENCH_recsys): a drop means the hot
# set fell out of its tier — a perf cliff even when examples/s survives
# on a fast host — and a healthy hot tier can sit anywhere in 0-100, so
# points, not ratios, are the meaningful band. accept% is the
# speculative-decoding draft acceptance rate (BENCH_serve,
# serve_spec_accept_pct): a drop means drafts stopped matching the
# verifier and every verify dispatch degrades toward a plain decode
# step — the same anywhere-in-0-100 shape as hit%, so absolute points.
# goodput% is the training goodput ledger's productive share
# (BENCH_train, train_goodput_pct): a drop means wall-clock leaked into
# a badput bucket — a point loss is a point loss whether the baseline
# sat at 99 or at 60, so absolute points again. swap% is the
# hot-swap-drill availability (BENCH_serve, serve_swap_availability_pct:
# fleet availability through 3 consecutive live weight swaps under mmpp
# load): it lives at ~100 where the relative band would hide a 9-point
# outage, so absolute points — a drop means the zero-downtime cutover
# started shedding or failing live requests.
_ABS_POINT_HIGHER_UNITS = {"weak%", "balance", "hit%", "accept%",
                           "goodput%", "swap%"}
# recsys rate-like units (BENCH_recsys) ride the default direction:
# examples/s (training/serving throughput) and ratio (dedup ratio —
# mean ids served per row fetched, >= 1) are higher-is-better relative,
# like tokens/s; listed here so the unit table is exhaustive.
# "adapters" (BENCH_serve, serve_lora_adapters_per_chip: distinct LoRA
# adapters servable per chip at the fixed p99 budget) is a capacity
# count — higher is better, default relative gating, like tokens/s.
_RATE_UNIT_EXAMPLES = {"examples/s", "ratio", "adapters"}


def _metric_list(record) -> List[dict]:
    """A BENCH record's parsed field is one metric dict (old rounds) or a
    list (round 5+); raw metric-line lists are accepted directly. Falls
    back to scraping JSON lines out of the stored stdout tail."""
    if isinstance(record, list):
        return [m for m in record if isinstance(m, dict) and "metric" in m]
    if isinstance(record, dict):
        if "metric" in record:
            return [record]
        parsed = record.get("parsed")
        if parsed is not None:
            return _metric_list(parsed)
        tail = record.get("tail", "")
        out = []
        for line in tail.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(d, dict) and "metric" in d:
                    out.append(d)
        return out
    return []


def lower_is_better(unit: str) -> bool:
    return unit.strip().lower() in _TIME_UNITS


def compare(old: List[dict], new: List[dict],
            tolerance: float = DEFAULT_TOLERANCE) -> List[str]:
    """Returns a list of human-readable regression messages (empty = ok)."""
    prev: Dict[str, dict] = {m["metric"]: m for m in old}
    problems: List[str] = []
    for m in new:
        name = m["metric"]
        ref = prev.get(name)
        if ref is None:
            continue                      # new metric: nothing to gate
        try:
            v_new, v_old = float(m["value"]), float(ref["value"])
        except (KeyError, TypeError, ValueError):
            problems.append(f"{name}: malformed value "
                            f"({m.get('value')!r} vs {ref.get('value')!r})")
            continue
        unit = str(m.get("unit", ref.get("unit", "")))
        if unit.strip().lower() in _ABS_POINT_UNITS:
            delta = v_new - v_old             # growth is the regression
            if delta > tolerance * 100.0:
                problems.append(
                    f"{name}: {v_old:g} -> {v_new:g} {unit} "
                    f"(+{delta:.1f} points, tolerance "
                    f"{tolerance * 100:.0f} points)")
            continue
        if unit.strip().lower() in _ABS_POINT_HIGHER_UNITS:
            delta = v_old - v_new             # a drop is the regression
            if delta > tolerance * 100.0:
                problems.append(
                    f"{name}: {v_old:g} -> {v_new:g} {unit} "
                    f"(-{delta:.1f} points, tolerance "
                    f"{tolerance * 100:.0f} points)")
            continue
        if v_old == 0:
            continue
        if lower_is_better(unit):
            ratio = v_new / v_old         # >1 means slower
            if ratio > 1 + tolerance:
                problems.append(
                    f"{name}: {v_old:g} -> {v_new:g} {unit} "
                    f"(+{(ratio - 1) * 100:.1f}%, tolerance "
                    f"{tolerance * 100:.0f}%)")
        else:
            ratio = v_new / v_old         # <1 means less throughput
            if ratio < 1 - tolerance:
                problems.append(
                    f"{name}: {v_old:g} -> {v_new:g} {unit} "
                    f"(-{(1 - ratio) * 100:.1f}%, tolerance "
                    f"{tolerance * 100:.0f}%)")
    missing = set(prev) - {m["metric"] for m in new}
    for name in sorted(missing):
        problems.append(f"{name}: metric disappeared from the new record")
    return problems


def compare_common(old: List[dict], new: List[dict],
                   tolerance: float = DEFAULT_TOLERANCE) -> List[str]:
    """Gate only the metrics present in BOTH records (no 'disappeared'
    check). This is the in-run self-gate's comparator: a ``--quick`` bench
    run (BERT only) or a run where a diagnostic leg failed must not log
    every intentionally-skipped benchmark as a false regression; the full
    cross-record CLI gate (:func:`compare`) keeps the disappearance check
    for CI use."""
    names = {m["metric"] for m in new}
    return compare([m for m in old if m.get("metric") in names], new,
                   tolerance)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    tol = DEFAULT_TOLERANCE
    if "--tolerance" in argv:
        i = argv.index("--tolerance")
        try:
            tol = float(argv[i + 1])
        except (IndexError, ValueError):
            print("--tolerance needs a float", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    if len(argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        with open(argv[0]) as f:
            old = _metric_list(json.load(f))
        with open(argv[1]) as f:
            new = _metric_list(json.load(f))
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read records: {e}", file=sys.stderr)
        return 2
    if not old:
        print(f"{argv[0]}: no metric lines found (nothing to gate)")
        return 0
    problems = compare(old, new, tol)
    if problems:
        print("BENCH REGRESSION:")
        for p in problems:
            print("  " + p)
        return 1
    print(f"bench gate ok: {len(new)} metric(s), none regressed beyond "
          f"{tol * 100:.0f}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
