"""Dev tool: trace the ResNet-50 train step; print top XLA ops."""
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import collections
import re
import time

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    jax.config.update("jax_default_prng_impl", "rbg")
    import paddle_tpu as paddle
    paddle.set_flags({"tpu_matmul_precision": "default"})
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.nn import functional as F
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.vision.models import resnet50

    B = int(os.environ.get("RN_B", "256"))
    fmt = os.environ.get("RN_FMT", "NCHW")
    # ablation toggles for docs/PERF_RESNET.md (layout × fusion × bf16):
    #   RN_CL=0    disable the TrainStep channels-last rewrite
    #   RN_FUSED=0 disable conv+BN+ReLU fusion
    #   RN_AMP=0   run full f32 (no bf16 activation stream)
    paddle.set_flags({
        "jit_channels_last": os.environ.get("RN_CL", "1") != "0",
        "fused_conv_bn": os.environ.get("RN_FUSED", "1") != "0",
    })
    use_amp = os.environ.get("RN_AMP", "1") != "0"
    paddle.seed(0)
    model = resnet50(num_classes=1000, data_format=fmt)

    def loss_fn(layer, xb, yb):
        with paddle.amp.auto_cast(enable=use_amp, level="O1"):
            return F.cross_entropy(layer(xb), yb)

    opt = Momentum(learning_rate=0.1, parameters=model.parameters(),
                   momentum=0.9, weight_decay=1e-4)
    step = TrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    import jax.numpy as jnp
    x = jnp.asarray(rng.normal(size=(B, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, (B,)).astype(np.int32))

    float(step(x, y))
    for _ in range(2):
        out = step(x, y)
    float(out)
    t0 = time.perf_counter()
    for _ in range(5):
        out = step(x, y)
    float(out)
    from paddle_tpu.core.flags import get_flag
    log(f"resnet50 B={B} {fmt} cl={int(get_flag('jit_channels_last'))} "
        f"fused={int(get_flag('fused_conv_bn'))} amp={int(use_amp)}: "
        f"{(time.perf_counter()-t0)/5*1e3:.1f} ms/step")

    tdir = "/tmp/rn_trace"
    os.system(f"rm -rf {tdir}")
    with jax.profiler.trace(tdir):
        for _ in range(3):
            out = step(x, y)
        float(out)
    paths = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
    if not paths:
        log("no trace captured")
        return
    with gzip.open(paths[0], "rt") as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    op_pids = {p for p, n in pid_names.items() if "TPU" in n or "XLA" in n}
    tot = collections.Counter()
    cnt = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in op_pids:
            name = e.get("name", "")
            if name.startswith("jit_") or name.isdigit():
                continue
            base = re.sub(r"[.\d_]+$", "", name) or name
            tot[base] += e.get("dur", 0)
            cnt[base] += 1
    total_us = sum(tot.values())
    log(f"total device op time: {total_us/3/1e3:.1f} ms/step over 3 steps")
    for name, us in tot.most_common(20):
        log(f"{us/3/1e3:8.2f} ms/step ({us/total_us*100:4.1f}%)  "
            f"x{cnt[name]:4d}  {name[:90]}")


if __name__ == "__main__":
    main()
