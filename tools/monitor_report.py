"""Render a monitor-registry JSONL dump as a human-readable report.

CI/tooling companion of paddle_tpu.monitor (the analogue of the
reference's profiler summary tables, but fed from the metrics registry):
given the append-only JSONL written by ``MetricsRegistry.dump_jsonl`` —
``BENCH_monitor.jsonl`` from bench.py, or an hapi ``MonitorCallback``
stream — prints:

- the top-k slowest timing histograms (by total seconds);
- compile/recompile counters (TrainStep jit entries + the process-wide
  jax backend-compile / persistent-cache / scan-trace gauges);
- comms traffic: bytes/ops/mean dispatch latency by (op, group);
- with ``--memory``: per-program HBM budget table
  (``train_step_program_*`` gauges) + the live-buffer census
  (``live_buffer_bytes`` by category, from monitor.memory);
- with ``--comms``: the latency-hiding view — overlapped-vs-exposed comm
  time per op from the ``comm_overlap_ms`` gauges ``bench.py
  --multichip`` publishes (phase = serial | exposed | overlapped; eager
  collectives are synchronous dispatches, so their table is all-exposed
  by construction) plus the pipeline schedule's comm-model gauges
  (``pipeline_comm_ops_per_step`` / ``pipeline_bubble_fraction``,
  docs/PARALLELISM.md);
- with ``--moe``: the MoE router-health view — a per-layer table of the
  ``moe_router_*`` gauges (balance/drop/entropy + per-expert load
  spread), the dropped-token counter, and expert-parallel fallback
  counts (docs/MOE.md; rendered next to the --comms output);
- with ``--serve``: the serving engine's per-request latency histograms
  (TTFT/TPOT/e2e/decode-step with approximate p50/p99), decode batching
  occupancy, queue-depth/slot/page gauges, serving program HBM
  budgets, and the multi-tenant view — per-tenant request outcomes and
  quota deferrals plus the LoRA adapter pool and quantized-KV
  footprint (``serve_*``/``serve_tenant_*``/``serve_lora_*`` series
  from paddle_tpu.serving; docs/SERVING.md);
- with ``--fleet``: the fleet router's per-replica table (queue depth,
  prefix hit%, shed counts) and routing/migration counters + route
  latency (``serve_router_*`` series from paddle_tpu.serving.router;
  docs/SERVING.md fleet topology; rendered before --serve so router
  series appear here, once);
- with ``--recsys``: the embedding-tier view — per-table occupancy and
  hit rates across the HBM/host/SSD tiers, promotion/eviction
  counters, per-table HBM attribution and sharded-lookup fallbacks
  (``recsys_*`` series from paddle_tpu.recsys; docs/RECSYS.md;
  rendered next to --serve/--moe);
- with ``--slo``: the error-budget burn table from the ``slo_*`` gauges
  (monitor/slo.py) — per SLO the objective, period budget remaining and
  burn rate per window (1.0 = spending exactly the budget; rendered
  next to --serve, which tells you *what* is failing while this tells
  you *how fast the budget goes*);
- with ``--lifecycle``: the zero-downtime model-push view — hot-swap
  event counters (``serve_swaps_total``), the live weights epoch and
  promotion-controller state, the state/epoch timeline from repeated
  dumps, per-arm shadow/A-B outcomes + latency and greedy
  shadow-divergence counts (``serve_lifecycle_*``/``serve_arm_*``
  series from paddle_tpu.serving.lifecycle; docs/SERVING.md "Model
  lifecycle"; rendered next to --serve/--slo);
- with ``--goodput``: the training goodput view — the
  ``train_goodput_pct`` gauge, cumulative badput seconds by exclusive
  bucket (``train_badput_seconds_total``), and the per-layer model
  health table (``train_layer_{grad_norm,param_norm,update_ratio}``
  gauges + ``train_health_spikes_total``) from the goodput ledger
  (monitor/goodput.py; docs/OBSERVABILITY.md "Training goodput & model
  health");
- with ``--fallbacks``: every counted degradation in ONE table — scan
  loop-layout, Pallas-kernel XLA, pipeline sequential-GSPMD, MoE and
  recsys auto-path fallbacks with reason labels ("why is this run
  slow" starts here, not at five separate counters);
- everything else (counters/gauges) as a flat table.

``--kernels`` needs no input file: it enumerates the live
``paddle_tpu.ops.pallas`` kernel registry — per kernel the kill-switch
flag and its current value, whether dispatch would serve the Pallas body
on THIS backend (``live``), the XLA fallback that serves otherwise, and
any fallback counts observed in this process (``PALLAS_STATS``; the
persistent view is the ``pallas_fallback_total{kernel,reason}`` counter
in a monitor dump, rendered by the default counter table).

``--flight`` switches input format entirely: the argument is a crash
flight-recorder dump (monitor/flight_recorder.py JSON) and the report
shows trip reason, environment fingerprint, a *recovery timeline*
(checkpoint commits/fallbacks, collective timeouts, non-finite skips,
preemptions, chaos fires — docs/FAULT_TOLERANCE.md), the event log and
the last-N step records.

``--trace`` also switches input format: the argument is a structured
trace dump (``monitor.trace.Tracer.dump`` JSON, or a flight-recorder
dump carrying a ``traces`` section) and the report renders each span
tree with per-span duration, EXCLUSIVE time and the critical path
(``*``), plus an exclusive-time-by-span attribution table
(docs/OBSERVABILITY.md "Structured tracing").

Usage:
    python tools/monitor_report.py BENCH_monitor.jsonl [--top 10] [--memory] [--serve] [--fleet] [--slo] [--lifecycle] [--goodput] [--comms] [--moe] [--recsys] [--fallbacks]
    python tools/monitor_report.py --flight flight_recorder_123.json [--last 20]
    python tools/monitor_report.py --trace traces.json [--last 20]
    python tools/monitor_report.py --kernels

Exit code: 0 on success (including an empty report), 2 on usage/read
errors. Append-only input is expected: the NEWEST sample per
(name, labels) wins.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple


def _latest_samples(rows: List[dict]) -> Dict[Tuple[str, tuple], dict]:
    """Newest line per (name, labels) — file order breaks ts ties, so the
    last appended dump wins."""
    out: Dict[Tuple[str, tuple], dict] = {}
    for row in rows:
        labels = tuple(sorted((row.get("labels") or {}).items()))
        out[(row["name"], labels)] = row
    return out


def _fmt_labels(labels: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in labels) if labels else "-"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}"
        n /= 1024
    return f"{n:,.1f} TiB"


def _table(title: str, headers: List[str],
           rows: List[List[str]]) -> List[str]:
    if not rows:
        return []
    widths = [max(len(h), *(len(r[i]) for r in rows))
              for i, h in enumerate(headers)]
    lines = [f"== {title} ==",
             "  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append("")
    return lines


def _comms_section(latest, used) -> List[str]:
    """--comms: overlapped-vs-exposed comm time per op. Traced pipeline
    collectives never hit the eager dispatch tracer, so their latency
    hiding is measured by ``bench.py --multichip`` (serial = the op's
    back-to-back eager time for the schedule's per-step traffic, exposed
    = the step-time residual the mesh run actually pays, overlapped =
    serial − exposed) and published as ``comm_overlap_ms`` gauges."""
    out: List[str] = []
    per: Dict[tuple, dict] = {}
    for key, row in latest.items():
        name, labels = key
        if name != "comm_overlap_ms":
            continue
        used.add(key)
        d = dict(labels)
        phase = str(d.pop("phase", "?"))
        per.setdefault(tuple(sorted(d.items())), {})[phase] = \
            float(row.get("value", 0.0))
    o_rows = []
    for labels, d in sorted(per.items()):
        serial = d.get("serial", 0.0)
        exposed = d.get("exposed", 0.0)
        overl = d.get("overlapped", max(0.0, serial - exposed))
        share = 100.0 * overl / serial if serial > 0 else 0.0
        o_rows.append([_fmt_labels(labels), f"{serial:,.2f}",
                       f"{exposed:,.2f}", f"{overl:,.2f}",
                       f"{share:.0f}%"])
    out += _table("Comm/compute overlap per op (bench.py --multichip)",
                  ["op/mesh/schedule", "serial ms", "exposed ms",
                   "overlapped ms", "hidden"], o_rows)
    m_rows = []
    for key in sorted(latest):
        name, labels = key
        if name in ("pipeline_comm_ops_per_step",
                    "pipeline_bubble_fraction",
                    "pipeline_fallback_total"):
            used.add(key)
            m_rows.append([name, _fmt_labels(labels),
                           f"{latest[key].get('value', 0):g}"])
    out += _table("Pipeline schedule comm model",
                  ["metric", "labels", "value"], m_rows)
    if not o_rows and not m_rows:
        out.append("(no comm-overlap or pipeline gauges in this dump — "
                   "run bench.py --multichip with FLAGS_monitor on)")
        out.append("")
    return out


def _goodput_section(latest, used) -> List[str]:
    """--goodput: training goodput ledger + per-layer model health.
    Buckets are EXCLUSIVE and sum to trainer wall-clock (the ledger's
    exhaustiveness invariant), so the badput table reads as a complete
    where-did-the-time-go attribution, not a sample."""
    out: List[str] = []
    g_rows = []
    for key in sorted(latest):
        name, labels = key
        if name in ("train_goodput_pct", "train_step_mfu"):
            used.add(key)
            g_rows.append([name, _fmt_labels(labels),
                           f"{latest[key].get('value', 0.0):,.2f}"])
    out += _table("Training goodput (FLAGS_train_goodput)",
                  ["metric", "labels", "value"], g_rows)
    b_rows = []
    for key, row in latest.items():
        name, labels = key
        if name != "train_badput_seconds_total":
            continue
        used.add(key)
        b_rows.append([str(dict(labels).get("bucket", "?")),
                       float(row.get("value", 0.0))])
    b_rows.sort(key=lambda r: -r[1])
    out += _table("Badput by bucket (exclusive, cumulative seconds)",
                  ["bucket", "seconds"],
                  [[b, f"{s:,.2f}"] for b, s in b_rows])
    # per-layer health gauges fold into one row per layer, worst grad
    # norm first — the monitor_top "top offenders" view, in full
    per: Dict[str, dict] = {}
    short = {"train_layer_grad_norm": "grad",
             "train_layer_param_norm": "param",
             "train_layer_update_ratio": "update"}
    for key, row in latest.items():
        name, labels = key
        if name in short:
            used.add(key)
            layer = str(dict(labels).get("layer", "?"))
            per.setdefault(layer, {})[short[name]] = \
                float(row.get("value", 0.0))
        elif name == "train_health_spikes_total":
            used.add(key)
            layer = str(dict(labels).get("layer", "?"))
            per.setdefault(layer, {})["spikes"] = \
                float(row.get("value", 0.0))
    l_rows = [[layer, f"{d.get('grad', 0.0):,.4g}",
               f"{d.get('param', 0.0):,.4g}",
               f"{d.get('update', 0.0):,.2e}",
               f"{d.get('spikes', 0.0):g}"]
              for layer, d in sorted(per.items(),
                                     key=lambda kv:
                                     -kv[1].get("grad", 0.0))]
    out += _table("Per-layer model health (FLAGS_train_health_every)",
                  ["layer", "grad norm", "param norm", "update ratio",
                   "spikes"], l_rows)
    if not g_rows and not b_rows and not l_rows:
        out.append("(no goodput series in this dump — train with "
                   "FLAGS_train_goodput on; per-layer health additionally "
                   "needs FLAGS_train_health_every=N)")
        out.append("")
    return out


def _moe_section(latest, used) -> List[str]:
    """--moe: per-layer router-health table from the ``moe_router_*``
    gauges MoE layers publish (balance/drop/entropy + per-expert load
    min/max spread), the dropped-token counter, and any
    ``moe_fallback_total`` telemetry — the routing-health companion to
    --comms' comm-overlap view (docs/MOE.md)."""
    per: Dict[str, dict] = {}
    loads: Dict[str, Dict[int, float]] = {}
    for key, row in latest.items():
        name, labels = key
        d = dict(labels)
        if name in ("moe_router_balance_pct", "moe_router_drop_pct",
                    "moe_router_entropy", "moe_dropped_tokens_total"):
            used.add(key)
            per.setdefault(str(d.get("layer", "-")), {})[name] = \
                row.get("value", 0.0)
        elif name == "moe_expert_load_share":
            used.add(key)
            loads.setdefault(str(d.get("layer", "-")), {})[
                int(d.get("expert", 0))] = row.get("value", 0.0)
    def _layer_key(name: str):
        # "layer10" must sort after "layer2": split the trailing int out
        import re
        m = re.match(r"^(.*?)(\d+)$", name)
        return (m.group(1), int(m.group(2))) if m else (name, -1)

    rows = []
    for layer in sorted(per | loads, key=_layer_key):
        d = per.get(layer, {})
        ld = loads.get(layer, {})
        spread = (f"{min(ld.values()):.3f}/{max(ld.values()):.3f}"
                  if ld else "-")
        rows.append([
            layer,
            f"{d.get('moe_router_balance_pct', 0.0):.1f}",
            f"{d.get('moe_router_drop_pct', 0.0):.1f}",
            f"{d.get('moe_router_entropy', 0.0):.3f}",
            spread,
            f"{d.get('moe_dropped_tokens_total', 0.0):g}"])
    out = _table("MoE router health (per layer)",
                 ["layer", "balance%", "drop%", "entropy",
                  "load min/max", "dropped total"], rows)
    f_rows = []
    for key in sorted(latest):
        name, labels = key
        if name == "moe_fallback_total":
            used.add(key)
            f_rows.append([name, _fmt_labels(labels),
                           f"{latest[key].get('value', 0):g}"])
    out += _table("MoE expert-parallel fallbacks",
                  ["counter", "labels", "value"], f_rows)
    if not rows and not f_rows:
        out.append("(no moe_router_* gauges in this dump — run an eager "
                   "MoE forward with FLAGS_monitor on, or "
                   "publish_moe_telemetry/publish_router_stats)")
        out.append("")
    return out


def _recsys_section(latest, used) -> List[str]:
    """--recsys: per-table tier occupancy, hit rates, promotion/eviction
    counters and HBM attribution from the ``recsys_*`` gauges the tier
    manager publishes (docs/RECSYS.md) — the embedding-tier companion
    to --serve's latency view."""
    occ: Dict[str, Dict[str, float]] = {}
    rates: Dict[str, Dict[str, float]] = {}
    hits: Dict[str, Dict[str, float]] = {}
    flow: Dict[str, Dict[str, float]] = {}
    hbm: Dict[str, float] = {}
    for key, row in latest.items():
        name, labels = key
        d = dict(labels)
        table = str(d.get("table", "-"))
        tier = str(d.get("tier", "-"))
        if name == "recsys_table_rows":
            used.add(key)
            occ.setdefault(table, {})[tier] = row.get("value", 0.0)
        elif name == "recsys_tier_hit_pct":
            used.add(key)
            rates.setdefault(table, {})[tier] = row.get("value", 0.0)
        elif name == "recsys_tier_hits_total":
            used.add(key)
            hits.setdefault(table, {})[tier] = row.get("value", 0.0)
        elif name in ("recsys_tier_promotions_total",
                      "recsys_tier_demotions_total",
                      "recsys_tier_evictions_total"):
            used.add(key)
            flow.setdefault(table, {})[
                name[len("recsys_tier_"):-len("_total")]] = \
                row.get("value", 0.0)
        elif name == "recsys_table_hbm_bytes":
            used.add(key)
            hbm[table] = row.get("value", 0.0)
    rows = []
    for table in sorted(set(occ) | set(rates) | set(hits) | set(flow)
                        | set(hbm)):
        o, r, f = occ.get(table, {}), rates.get(table, {}), \
            flow.get(table, {})
        rows.append([
            table,
            "/".join(f"{int(o.get(t, 0))}" for t in ("hbm", "host",
                                                     "ssd")),
            "/".join(f"{r.get(t, 0.0):.1f}" for t in ("hbm", "host",
                                                      "ssd")),
            f"{sum(hits.get(table, {}).values()):g}",
            f"{f.get('promotions', 0):g}",
            f"{f.get('evictions', 0):g}",
            _fmt_bytes(hbm.get(table, 0.0))])
    out = _table("Recsys embedding tiers (per table)",
                 ["table", "rows hbm/host/ssd", "hit% hbm/host/ssd",
                  "fetches", "promoted", "evicted", "HBM bytes"], rows)
    f_rows = []
    for key in sorted(latest):
        name, labels = key
        if name == "recsys_fallback_total":
            used.add(key)
            f_rows.append([name, _fmt_labels(labels),
                           f"{latest[key].get('value', 0):g}"])
    out += _table("Recsys sharded-lookup fallbacks",
                  ["counter", "labels", "value"], f_rows)
    if not rows and not f_rows:
        out.append("(no recsys_* gauges in this dump — run bench.py "
                   "--recsys or publish_tier_metrics() first)")
        out.append("")
    return out


def _slo_section(latest, used) -> List[str]:
    """--slo: error-budget burn table from the ``slo_*`` gauges
    (monitor/slo.py; PR 11 emits them, this mode renders them) — per
    SLO the configured objective, the period budget remaining, and the
    burn rate per configured window (1.0 = spending exactly the
    budget; the SRE-workbook alert pairs fire around 6-14x). Rendered
    next to --serve/--trace/--fallbacks."""
    objective: Dict[str, float] = {}
    remaining: Dict[str, float] = {}
    burns: Dict[str, Dict[str, float]] = {}
    for key, row in latest.items():
        name, labels = key
        d = dict(labels)
        if name == "slo_objective":
            used.add(key)
            objective[str(d.get("slo", "-"))] = row.get("value", 0.0)
        elif name == "slo_error_budget_remaining":
            used.add(key)
            remaining[str(d.get("slo", "-"))] = row.get("value", 0.0)
        elif name == "slo_burn_rate":
            used.add(key)
            burns.setdefault(str(d.get("slo", "-")), {})[
                str(d.get("window", "?"))] = row.get("value", 0.0)

    def _window_key(w: str):
        try:
            return (0, float(w.rstrip("s")))
        except ValueError:
            return (1, 0.0)

    windows = sorted({w for d in burns.values() for w in d},
                     key=_window_key)
    rows = []
    for slo in sorted(set(objective) | set(remaining) | set(burns)):
        b = burns.get(slo, {})
        rem = remaining.get(slo)
        rows.append(
            [slo,
             f"{objective.get(slo, 0.0):.4g}" if slo in objective
             else "-",
             (f"{rem:.3f}" + (" (BLOWN)" if rem < 0 else ""))
             if rem is not None else "-"]
            + [f"{b[w]:.2f}" if w in b else "-" for w in windows])
    out = _table("SLO error-budget burn (1.0 = on budget)",
                 ["slo", "objective", "budget left"]
                 + [f"burn {w}" for w in windows], rows)
    if not rows:
        out = ["== SLO burn ==",
               "(no slo_* gauges in this dump — arm "
               "ServingConfig.slo_availability / slo_deadline, or call "
               "SLOTracker.publish())", ""]
    return out


#: lifecycle-state gauge codes (serve_lifecycle_state) — fallback copy
#: for a standalone checkout; the live tuple is
#: paddle_tpu.serving.lifecycle.STATES and a sync-pin test keeps them
#: from drifting
_LIFECYCLE_STATES_FALLBACK = ("serving", "staging", "baking", "promoted",
                              "rolled-back")


def _lifecycle_states() -> tuple:
    try:
        from paddle_tpu.serving.lifecycle import STATES
        return tuple(STATES)
    except Exception:
        return _LIFECYCLE_STATES_FALLBACK


def _lifecycle_timeline(rows: List[dict], used) -> List[str]:
    """Controller-state timeline from EVERY serve_lifecycle_state and
    serve_weights_epoch sample in the (append-only) dump, in file
    order — repeated registry dumps trace a staged push through
    staging -> baking -> promoted (or rolled-back), interleaved with
    the epoch bumps of each cutover."""
    states = _lifecycle_states()
    samples = [r for r in rows
               if r.get("name") in ("serve_lifecycle_state",
                                    "serve_weights_epoch")]
    if not samples:
        return []
    t0 = next((r["ts"] for r in samples
               if isinstance(r.get("ts"), (int, float))), None)
    out, last = [], {}
    for r in samples:
        name = r["name"]
        used.add((name, tuple(sorted((r.get("labels") or {}).items()))))
        v = r.get("value")
        if name == "serve_lifecycle_state":
            code = int(v or 0)
            what = (states[code] if 0 <= code < len(states)
                    else f"state {code}")
        else:
            what = f"weights epoch -> {v:g}" if v is not None else "-"
        if last.get(name) == what:
            continue
        last[name] = what
        ts = r.get("ts")
        rel = (f"+{ts - t0:.2f}s"
               if isinstance(ts, (int, float)) and t0 is not None
               else "-")
        out.append([rel, what])
    return _table("Lifecycle timeline", ["t", "event"], out)


def _lifecycle_section(latest, used,
                       raw_rows: Optional[List[dict]] = None) -> List[str]:
    """--lifecycle: the zero-downtime model-push view (docs/SERVING.md
    "Model lifecycle") — hot-swap event counters
    (``serve_swaps_total{event}``), the live weights epoch and
    controller state, the state/epoch timeline, per-arm shadow/A-B
    outcomes + latency (``serve_arm_*``) and greedy shadow-divergence
    counts, plus the candidate's burn gauges when an SLOTracker named
    ``lifecycle_*`` published (peeked, not claimed — ``--slo`` still
    renders the full burn table). Rendered next to --serve/--slo."""
    states = _lifecycle_states()
    swap_rows, s_rows = [], []
    arm_counts: Dict[str, Dict[str, float]] = {}
    arm_lat: Dict[str, dict] = {}
    divergence = None
    for key in sorted(latest):
        name, labels = key
        row = latest[key]
        d = dict(labels)
        if name == "serve_swaps_total":
            used.add(key)
            swap_rows.append([str(d.get("event", "-")),
                              f"{row.get('value', 0):g}"])
        elif name == "serve_weights_epoch":
            used.add(key)
            s_rows.append(["live weights epoch",
                           f"{row.get('value', 0):g}"])
        elif name == "serve_lifecycle_state":
            used.add(key)
            code = int(row.get("value") or 0)
            s_rows.append(["controller state",
                           states[code] if 0 <= code < len(states)
                           else f"state {code}"])
        elif name == "serve_lifecycle_transitions_total":
            used.add(key)
            s_rows.append([f"transitions -> {d.get('to', '-')}",
                           f"{row.get('value', 0):g}"])
        elif name == "serve_arm_requests_total":
            used.add(key)
            arm_counts.setdefault(str(d.get("arm", "-")), {})[
                str(d.get("event", "-"))] = row.get("value", 0.0)
        elif name == "serve_arm_e2e_seconds":
            used.add(key)
            arm_lat[str(d.get("arm", "-"))] = row
        elif name == "serve_shadow_divergence_total":
            used.add(key)
            divergence = row.get("value", 0.0)
    out = _table("Lifecycle (hot-swap push state)",
                 ["what", "value"], s_rows)
    out += _table("Weight-swap events (serve_swaps_total)",
                  ["event", "count"], swap_rows)
    a_rows = []
    for arm in sorted(set(arm_counts) | set(arm_lat)):
        counts = arm_counts.get(arm, {})
        lat = arm_lat.get(arm)
        n = int(lat.get("count") or 0) if lat else 0
        mean = (lat["sum"] / n * 1e3) if lat and n else 0.0
        p99 = _hist_pct(lat, 0.99) if lat else None
        a_rows.append(
            [arm, f"{sum(counts.values()):g}",
             ",".join(f"{e}={v:g}" for e, v in sorted(counts.items()))
             or "-",
             f"{mean:,.2f}" if n else "-",
             f"<= {p99 * 1e3:,.1f}" if p99 is not None else "-"])
    out += _table("Shadow/A-B arms",
                  ["arm", "requests", "outcomes", "mean e2e ms",
                   "~p99 ms"], a_rows)
    if divergence is not None:
        out += [f"  greedy shadow divergences: {divergence:g}", ""]
    # candidate burn at a glance — peek the lifecycle_* SLO gauges
    # WITHOUT used.add so --slo (rendered before this section) keeps
    # its full table and the generic tables stay deduplicated there
    b_rows = []
    for key in sorted(latest):
        name, labels = key
        d = dict(labels)
        if (name == "slo_burn_rate"
                and str(d.get("slo", "")).startswith("lifecycle")):
            b_rows.append([str(d.get("slo")), str(d.get("window", "?")),
                           f"{latest[key].get('value', 0.0):.2f}"])
    out += _table("Candidate burn (slo_burn_rate, 1.0 = on budget)",
                  ["slo", "window", "burn"], b_rows)
    out += _lifecycle_timeline(raw_rows or [], used)
    if not out:
        out = ["== Lifecycle ==",
               "(no serve_swaps_total / serve_lifecycle_* metrics in "
               "this dump — enable FLAGS_serve_hot_swap and push a "
               "manifest through ServingEngine.swap_weights or "
               "LifecycleController.begin first)", ""]
    return out


#: the counted-degradation counters every subsystem publishes when its
#: primary path cannot serve (docs: PERF_TRANSFORMER/PERF_KERNELS/
#: PARALLELISM/MOE/RECSYS); one table answers "why is this run slow"
#: instead of five separate counter greps
_FALLBACK_COUNTERS = ("scan_fallback_total", "pallas_fallback_total",
                      "pipeline_fallback_total", "moe_fallback_total",
                      "recsys_fallback_total")


def _fallbacks_section(latest, used) -> List[str]:
    """--fallbacks: every counted degradation in one table — scan
    loop-layout fallbacks, Pallas-kernel XLA fallbacks, pipeline
    sequential-GSPMD degradations and MoE auto-path fallbacks, each
    with its reason labels."""
    rows = []
    total = 0.0
    for cname in _FALLBACK_COUNTERS:
        for key in sorted(latest):
            name, labels = key
            if name != cname:
                continue
            used.add(key)
            v = float(latest[key].get("value", 0.0))
            total += v
            rows.append([name[:-len("_fallback_total")],
                         _fmt_labels(labels), f"{v:g}"])
    if not rows:
        return ["== Fallbacks / degradations ==",
                "(no *_fallback_total counters in this dump — every "
                "subsystem served its primary path, or FLAGS_monitor "
                "was off while they fell back)", ""]
    return _table(f"Fallbacks / degradations ({total:g} total)",
                  ["subsystem", "reason", "count"], rows)


def _memory_section(latest, used) -> List[str]:
    """--memory: per-program HBM budgets + the live-buffer census."""
    prog: Dict[str, dict] = {}
    for key, row in latest.items():
        name, labels = key
        if name.startswith("train_step_program_"):
            used.add(key)
            kind = dict(labels).get("kind", "-")
            prog.setdefault(kind, {})[
                name[len("train_step_program_"):]] = row.get("value", 0.0)
    p_rows = []
    for kind in sorted(prog):
        d = prog[kind]
        flops, acc = d.get("flops", 0.0), d.get("bytes_accessed", 0.0)
        p_rows.append([kind, _fmt_bytes(d.get("peak_hbm_bytes", 0.0)),
                       f"{flops:.3e}", _fmt_bytes(acc),
                       f"{flops / acc:.1f}" if acc else "-"])
    out = _table("Program HBM budgets (static, per kind)",
                 ["kind", "peak HBM est.", "flops", "bytes accessed",
                  "arith. int."], p_rows)
    c_rows = []
    for key in sorted(latest):
        name, labels = key
        if name in ("live_buffer_bytes", "live_buffer_count"):
            used.add(key)
            if name == "live_buffer_bytes":
                cat = dict(labels).get("category", "-")
                n = latest.get(("live_buffer_count", labels), {})
                c_rows.append([cat,
                               _fmt_bytes(latest[key].get("value", 0.0)),
                               f"{n.get('value', 0):g}"])
    out += _table("Live-buffer census", ["category", "bytes", "arrays"],
                  c_rows)
    return out


def _hist_pct(row: dict, q: float) -> Optional[float]:
    """Approximate quantile from a cumulative-`le` histogram sample: the
    smallest bucket upper bound covering fraction ``q`` of observations
    (None when empty or when the quantile falls past the last bucket)."""
    count = row.get("count") or 0
    if not count:
        return None
    target = q * count
    for le, cum in row.get("buckets") or []:
        if cum >= target:
            return float(le)
    return None


#: canonical request-outcome order for the --serve table: offered
#: traffic first (submitted + never-admitted rejections), then the
#: terminal outcomes per paddle_tpu.serving.scheduler.TERMINAL_OUTCOMES
_OUTCOME_ORDER = ("submitted", "rejected", "completed", "expired",
                  "shed", "cancelled", "failed", "drained")


def _serve_outcomes(latest, used) -> List[str]:
    """Request-outcome table from serve_requests_total{event=...}: where
    every request ended up (zero-lost accounting — docs/SERVING.md,
    "Operating under overload and failure"). Terminal outcomes are a
    share of SUBMITTED requests; "rejected" (refused at admission,
    never submitted) is a share of OFFERED = submitted + rejected."""
    counts = {}
    for key, row in latest.items():
        name, labels = key
        if name != "serve_requests_total":
            continue
        used.add(key)
        counts[dict(labels).get("event", "?")] = row.get("value", 0.0)
    if not counts:
        return []
    submitted = counts.get("submitted", 0.0)
    offered = submitted + counts.get("rejected", 0.0)
    rows = []
    for ev in list(_OUTCOME_ORDER) + sorted(set(counts) -
                                            set(_OUTCOME_ORDER)):
        if ev not in counts:
            continue
        if ev == "submitted":
            pct = (f"{100.0 * submitted / offered:.1f}% of offered"
                   if offered else "-")
        elif ev == "rejected":
            pct = (f"{100.0 * counts[ev] / offered:.1f}% of offered"
                   if offered else "-")
        else:
            pct = (f"{100.0 * counts[ev] / submitted:.1f}% of submitted"
                   if submitted else "-")
        rows.append([ev, f"{counts[ev]:g}", pct])
    return _table("Request outcomes", ["event", "count", "share"],
                  rows)


def _prefix_cache_section(latest, used) -> List[str]:
    """Radix prefix cache (ISSUE 15): occupancy, hit/miss/evict
    counters and the token-level hit share — the 'is chat traffic
    actually sharing prefixes' panel next to the outcome table."""
    vals = {}
    for key, row in latest.items():
        name, _ = key
        if name in ("serve_prefix_cached_pages",
                    "serve_prefix_hits_total",
                    "serve_prefix_misses_total",
                    "serve_prefix_hit_tokens_total",
                    "serve_prefix_evicted_pages_total"):
            used.add(key)
            vals[name] = row.get("value", 0.0)
    if not vals:
        return []
    hits = vals.get("serve_prefix_hits_total", 0.0)
    misses = vals.get("serve_prefix_misses_total", 0.0)
    lookups = hits + misses
    rows = [
        ["cached pages", f"{vals.get('serve_prefix_cached_pages', 0):g}"],
        ["admission hits", f"{hits:g}"
         + (f"  ({100.0 * hits / lookups:.1f}% of lookups)"
            if lookups else "")],
        ["admission misses", f"{misses:g}"],
        ["tokens served from cache",
         f"{vals.get('serve_prefix_hit_tokens_total', 0):g}"],
        ["pages evicted",
         f"{vals.get('serve_prefix_evicted_pages_total', 0):g}"],
    ]
    return _table("Prefix cache (radix tree over KV pages)",
                  ["stat", "value"], rows)


def _spec_decode_section(latest, used) -> List[str]:
    """Speculative decoding (ISSUE 15): proposed/accepted/rolled-back
    draft counters and the acceptance rate — accepted tokens rode a
    shared verify dispatch instead of their own decode step."""
    vals = {}
    for key, row in latest.items():
        name, _ = key
        if name in ("serve_spec_proposed_total",
                    "serve_spec_accepted_total",
                    "serve_spec_rolled_back_total"):
            used.add(key)
            vals[name] = row.get("value", 0.0)
    if not vals:
        return []
    prop = vals.get("serve_spec_proposed_total", 0.0)
    acc = vals.get("serve_spec_accepted_total", 0.0)
    rows = [
        ["drafts proposed", f"{prop:g}"],
        ["drafts accepted", f"{acc:g}"
         + (f"  ({100.0 * acc / prop:.1f}% acceptance)" if prop else "")],
        ["drafts rolled back",
         f"{vals.get('serve_spec_rolled_back_total', 0):g}"],
    ]
    return _table("Speculative decoding (n-gram drafts)",
                  ["stat", "value"], rows)


def _tenant_section(latest, used) -> List[str]:
    """Multi-tenant serving (ISSUE 17): the per-tenant table — requests
    by lifecycle event from ``serve_tenant_requests_total{tenant,event}``
    and quota deferrals from
    ``serve_tenant_quota_deferrals_total{tenant}`` — plus the engine-
    wide LoRA pool (adapters loaded / hot-swaps) and quantized-KV
    footprint lines. Runs before the generic serve_* catch-all so the
    tenant-labeled series render here, once."""
    per: Dict[str, dict] = {}
    pool = {}
    for key, row in latest.items():
        name, labels = key
        if name == "serve_tenant_requests_total":
            used.add(key)
            lab = dict(labels)
            d = per.setdefault(lab.get("tenant", "?"), {})
            d[lab.get("event", "?")] = row.get("value", 0.0)
        elif name == "serve_tenant_quota_deferrals_total":
            used.add(key)
            per.setdefault(dict(labels).get("tenant", "?"),
                           {})["quota"] = row.get("value", 0.0)
        elif name in ("serve_lora_swaps_total",
                      "serve_lora_adapters_loaded",
                      "serve_kv_quant_bytes_per_token"):
            used.add(key)
            pool[name] = row.get("value", 0.0)
    out: List[str] = []
    rows = [
        [t,
         f"{d.get('submitted', 0):g}",
         f"{d.get('completed', 0):g}",
         f"{d.get('failed', 0) + d.get('expired', 0) + d.get('shed', 0):g}",
         f"{d.get('quota', 0):g}"]
        for t, d in sorted(per.items())]
    out += _table("Tenants", ["tenant", "submitted", "completed",
                              "failed/expired/shed", "quota deferrals"],
                  rows)
    if pool:
        prows = []
        if "serve_lora_adapters_loaded" in pool:
            prows.append(["LoRA adapters loaded",
                          f"{pool['serve_lora_adapters_loaded']:g}"])
        if "serve_lora_swaps_total" in pool:
            prows.append(["LoRA adapter hot-swaps",
                          f"{pool['serve_lora_swaps_total']:g}"])
        if "serve_kv_quant_bytes_per_token" in pool:
            prows.append(["quantized KV bytes/token",
                          f"{pool['serve_kv_quant_bytes_per_token']:g}"])
        out += _table("Multi-tenant pool (LoRA + quantized KV)",
                      ["stat", "value"], prows)
    return out


def _overload_timeline(rows: List[dict], used) -> List[str]:
    """Overload-state timeline from EVERY serve_overload sample in the
    (append-only) dump, in file order — each registry dump contributes
    one point, so repeated dumps trace the shedding episodes."""
    samples = [r for r in rows if r.get("name") == "serve_overload"]
    if not samples:
        return []
    used.add(("serve_overload", tuple()))
    t0 = next((r["ts"] for r in samples
               if isinstance(r.get("ts"), (int, float))), None)
    out, last = [], None
    for r in samples:
        state = "OVERLOADED (shedding)" if r.get("value") else "normal"
        if state == last:
            continue
        last = state
        ts = r.get("ts")
        rel = (f"+{ts - t0:.2f}s"
               if isinstance(ts, (int, float)) and t0 is not None
               else "-")
        out.append([rel, state])
    return _table("Overload state timeline", ["t", "state"], out)


def _fleet_section(latest, used) -> List[str]:
    """--fleet: the router's per-replica table (queue depth, prefix
    hit%, shed count — the ``serve_router_replica_*`` gauges) plus the
    fleet routing/migration counters and route-decision latency
    (docs/SERVING.md fleet topology). Runs BEFORE --serve's generic
    serve_* catch-all so router series render here, once."""
    per: Dict[str, dict] = {}
    totals = []
    for key in sorted(latest):
        name, labels = key
        if not name.startswith("serve_router_"):
            continue
        row = latest[key]
        used.add(key)
        lab = dict(labels)
        rep = lab.get("replica")
        if rep is not None:
            per.setdefault(rep, {})[name] = row.get("value", 0)
        elif name == "serve_router_route_seconds":
            n = int(row.get("count") or 0)
            mean = (row["sum"] / n * 1e3) if n else 0.0
            p99 = _hist_pct(row, 0.99)
            totals.append([name, _fmt_labels(labels),
                           f"{n} routed, mean {mean:,.3f} ms, ~p99 <= "
                           f"{(p99 or 0) * 1e3:,.3f} ms"])
        else:
            totals.append([name, _fmt_labels(labels),
                           f"{row.get('value', 0):g}"])
    rep_rows = [
        [rep,
         f"{d.get('serve_router_replica_queue_depth', 0):g}",
         f"{d.get('serve_router_replica_prefix_hit_pct', 0):.1f}",
         f"{d.get('serve_router_replica_shed_requests', 0):g}"]
        for rep, d in sorted(per.items())]
    out = _table("Fleet replicas (router view)",
                 ["replica", "queue depth", "prefix hit%", "shed"],
                 rep_rows)
    out += _table("Fleet router counters", ["metric", "labels", "value"],
                  totals)
    if not out:
        out = ["== Fleet ==", "(no serve_router_* metrics in this dump "
               "— run a FleetRouter first)", ""]
    return out


def _serve_section(latest, used, raw_rows: Optional[List[dict]] = None) \
        -> List[str]:
    """--serve: per-request latency histograms, request outcomes, the
    overload timeline + queue/occupancy gauges from the serving engine's
    registry stream (docs/SERVING.md)."""
    lat_rows = []
    for name in ("serve_ttft_seconds", "serve_tpot_seconds",
                 "serve_e2e_seconds", "serve_decode_step_seconds",
                 "serve_prefill_seconds"):
        for key, row in sorted(latest.items()):
            if key[0] != name or row.get("type") != "histogram":
                continue
            used.add(key)
            n = int(row.get("count") or 0)
            mean = (row["sum"] / n * 1e3) if n else 0.0
            p50, p99 = _hist_pct(row, 0.50), _hist_pct(row, 0.99)
            fmt = lambda v: f"<= {v * 1e3:,.1f}" if v is not None else "-"
            lat_rows.append([name[len("serve_"):], _fmt_labels(key[1]),
                             str(n), f"{mean:,.2f}", fmt(p50), fmt(p99)])
    out = _table("Serving latency (per-request histograms)",
                 ["series", "labels", "count", "mean ms", "~p50 ms",
                  "~p99 ms"], lat_rows)
    out += _serve_outcomes(latest, used)
    out += _prefix_cache_section(latest, used)
    out += _spec_decode_section(latest, used)
    out += _tenant_section(latest, used)
    out += _overload_timeline(raw_rows or [], used)
    occ_rows, g_rows, c_rows, prog_rows = [], [], [], []
    for key in sorted(latest):
        name, labels = key
        if not name.startswith("serve_") or key in used:
            continue
        row = latest[key]
        used.add(key)
        if name == "serve_decode_occupancy":
            n = int(row.get("count") or 0)
            mean = row["sum"] / n if n else 0.0
            occ_rows.append([str(n), f"{mean:,.2f}",
                             f"{_hist_pct(row, 1.0) or 0:g}"])
        elif name == "serve_program_peak_hbm_bytes":
            prog_rows.append([dict(labels).get("kind", "-"),
                              _fmt_bytes(row.get("value", 0.0))])
        elif row.get("type") == "gauge":
            g_rows.append([name, _fmt_labels(labels),
                           f"{row.get('value', 0):g}"])
        elif row.get("type") == "counter":
            c_rows.append([name, _fmt_labels(labels),
                           f"{row.get('value', 0):g}"])
    out += _table("Decode batching", ["dispatches", "mean occupancy",
                                      "max bucket"], occ_rows)
    out += _table("Queue / slots / pages (gauges)",
                  ["gauge", "labels", "value"], g_rows)
    out += _table("Serving counters", ["counter", "labels", "value"],
                  c_rows)
    out += _table("Serving program HBM budgets",
                  ["kind", "peak HBM est."], prog_rows)
    if not out:
        out = ["== Serving ==", "(no serve_* metrics in this dump — "
               "run bench.py --serve or a ServingEngine first)", ""]
    return out


# recovery-timeline event names: the canonical tuple lives in
# paddle_tpu.monitor.flight_recorder.RECOVERY_EVENTS and is imported
# lazily; this fallback copy ONLY serves a standalone checkout where
# the framework cannot import (and a sync-pin test asserts it can
# never drift from the canonical tuple)
_RECOVERY_EVENTS_FALLBACK = (
    "checkpoint_commit", "checkpoint_fallback", "collective_timeout",
    "nonfinite_skip", "preempted", "trip", "chaos", "request_failed",
    "request_expired", "request_cancelled", "request_drained",
    "request_shed", "decode_watchdog", "overload", "drained",
    "replica_migration", "health_spike")


def _recovery_events() -> tuple:
    try:
        from paddle_tpu.monitor.flight_recorder import RECOVERY_EVENTS
        return RECOVERY_EVENTS
    except Exception:
        return _RECOVERY_EVENTS_FALLBACK


def _recovery_section(events: List[dict]) -> List[str]:
    """Chronological fault/recovery timeline: what failed, what the
    runtime did about it, relative to the first recovery event."""
    recov = [r for r in events if r.get("event") in _recovery_events()]
    if not recov:
        return []
    t0 = next((r["ts"] for r in recov
               if isinstance(r.get("ts"), (int, float))), None)
    rows = []
    for r in recov:
        ts = r.get("ts")
        rel = (f"+{ts - t0:.2f}s" if isinstance(ts, (int, float))
               and t0 is not None else "-")
        detail = ", ".join(f"{k}={v}" for k, v in sorted(r.items())
                           if k not in ("event", "ts"))
        rows.append([rel, str(r.get("event")), detail])
    return _table(f"Recovery timeline ({len(recov)} events)",
                  ["t", "event", "detail"], rows)


def render_flight(doc: dict, last: int = 10) -> str:
    """Render a flight-recorder dump: trip reason, fingerprint, the
    fault/recovery timeline, events, last-N step records."""
    lines = ["== Flight recorder dump =="]
    reason = doc.get("reason", "?")
    trip = doc.get("trip_step")
    lines.append(f"reason: {reason}"
                 + (f" (trip at step {trip})" if trip is not None else ""))
    if doc.get("exception"):
        lines.append(f"exception: {doc['exception']}")
    fp = doc.get("fingerprint") or {}
    lines.append("fingerprint: " + (", ".join(
        f"{k}={fp[k]}" for k in sorted(fp) if k != "argv") or "(none)"))
    lines.append("")
    # goodput dump provider (monitor/goodput.py): the ledger snapshot
    # at trip time — how much of the run's wall-clock was productive
    # when this dump fired, and where the rest went
    gp = doc.get("goodput")
    if isinstance(gp, dict):
        lines.append(f"goodput: {float(gp.get('goodput_pct', 0)):,.1f}% "
                     f"of {float(gp.get('elapsed_s', 0)):,.1f}s "
                     f"productive ({int(gp.get('restarts', 0))} "
                     "prior restarts)")
        b_rows = [[b, f"{float(s):,.2f}"]
                  for b, s in sorted((gp.get("buckets") or {}).items(),
                                     key=lambda kv: -float(kv[1]))
                  if float(s) > 0]
        if b_rows:
            lines.append("")
            lines += _table("Goodput buckets at dump (seconds)",
                            ["bucket", "seconds"], b_rows)
    lh = doc.get("layer_health")
    if isinstance(lh, dict) and lh.get("layers"):
        h_rows = [[layer, f"{float(d.get('grad_norm', 0)):,.4g}",
                   f"{float(d.get('param_norm', 0)):,.4g}",
                   f"{float(d.get('update_ratio', 0)):,.2e}"]
                  for layer, d in sorted(
                      lh["layers"].items(),
                      key=lambda kv:
                      -float(kv[1].get("grad_norm", 0)))]
        lines.append("")
        lines += _table("Last layer-health vector "
                        f"(step {lh.get('step', '?')})",
                        ["layer", "grad norm", "param norm",
                         "update ratio"], h_rows)
    ev = doc.get("events") or []
    lines += _recovery_section(ev)
    e_rows = [[str(r.get("event", "?")),
               str(r.get("kind", r.get("op", "-"))),
               str(r.get("step", "-")),
               ", ".join(f"{k}={v}" for k, v in sorted(r.items())
                         if k not in ("event", "kind", "op", "step",
                                      "ts"))]
              for r in ev[-last:]]
    lines += _table(f"Events (last {min(last, len(ev))} of {len(ev)})",
                    ["event", "what", "step", "detail"], e_rows)
    steps = doc.get("steps") or []
    s_rows = []
    for r in steps[-last:]:
        def num(v, fmt="{:.3f}"):
            return fmt.format(v) if isinstance(v, (int, float)) \
                else (str(v) if v is not None else "-")
        s_rows.append([str(r.get("step", "-")), str(r.get("kind", "-")),
                       num(r.get("loss"), "{:.5f}"),
                       num(r.get("wall_ms")), num(r.get("dispatch_ms")),
                       str(r.get("seed", "-"))])
    lines += _table(f"Step records (last {min(last, len(steps))} of "
                    f"{len(steps)}, ring capacity "
                    f"{doc.get('capacity', '?')})",
                    ["step", "kind", "loss", "wall ms", "dispatch ms",
                     "seed"], s_rows)
    if not ev and not steps:
        lines.append("(no step records or events in this dump)")
    return "\n".join(lines).rstrip() + "\n"


def _span_times(tdoc: dict):
    """(spans, children, dur, end) helpers for one trace dict; open
    spans render as zero-duration at their start."""
    spans = [s for s in (tdoc.get("spans") or [])
             if s.get("t0") is not None]
    by_id = {s["span_id"]: s for s in spans}
    children: Dict[int, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        pid = s.get("parent_id")
        if pid is None or pid not in by_id:
            roots.append(s)
        else:
            children.setdefault(pid, []).append(s)
    for v in children.values():
        v.sort(key=lambda s: (s["t0"], s["span_id"]))

    def end(s):
        return s["t1"] if s.get("t1") is not None else s["t0"]

    def dur(s):
        return max(0.0, end(s) - s["t0"])

    return spans, roots, children, dur, end


def _render_one_trace(tdoc: dict,
                      agg: Dict[str, List[float]]) -> List[str]:
    """One trace's span tree: per-span duration, EXCLUSIVE time
    (duration minus direct children — where the time actually went) and
    a ``*`` on the critical path (the root-to-leaf chain through each
    level's latest-ending child). ``agg`` accumulates exclusive time by
    normalized span name across traces."""
    import re
    spans, roots, children, dur, end = _span_times(tdoc)
    # critical path: descend into the child that finishes last
    crit = set()
    for r in roots:
        node = r
        while node is not None:
            crit.add(node["span_id"])
            kids = children.get(node["span_id"])
            node = max(kids, key=end) if kids else None
    excl = {}
    for s in spans:
        kids = children.get(s["span_id"], [])
        excl[s["span_id"]] = max(
            0.0, dur(s) - sum(dur(k) for k in kids))
        agg.setdefault(re.sub(r"\[\d+\]$", "", s["name"]),
                       [0.0, 0])[0] += excl[s["span_id"]]
        agg[re.sub(r"\[\d+\]$", "", s["name"])][1] += 1
    head = (f"-- trace {tdoc.get('trace_id', '?')} "
            f"({tdoc.get('name', '?')})")
    if tdoc.get("anomaly"):
        head += f"  ANOMALY: {tdoc['anomaly']}"
    if not tdoc.get("finished", True):
        head += "  [open]"
    head += ("  [head-sampled]" if tdoc.get("head_sampled")
             else "  [tail-kept]")
    lines = [head,
             f"  {'span':<34} {'ms':>9} {'excl ms':>9}  detail"]

    def walk(s, depth):
        mark = "*" if s["span_id"] in crit else " "
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted((s.get("attrs") or {}).items())
            if v is not None)
        name = ("  " * depth + s["name"])[:34]
        lines.append(f"{mark} {name:<34} {dur(s) * 1e3:>9.3f} "
                     f"{excl[s['span_id']] * 1e3:>9.3f}  {detail}")
        for k in children.get(s["span_id"], []):
            walk(k, depth + 1)

    for r in roots:
        walk(r, 0)
    lines.append("")
    return lines


def render_traces(traces: List[dict], last: int = 10) -> str:
    """--trace: span trees with critical-path (*) and exclusive-time
    attribution, from a ``Tracer.dump`` file (or the ``traces`` section
    of a flight-recorder dump)."""
    if not traces:
        return ("(no traces in this dump — run with FLAGS_trace on; "
                "healthy traffic is head-sampled at FLAGS_trace_sample, "
                "anomalies are always kept)\n")
    anom = sum(1 for t in traces if t.get("anomaly"))
    lines = [f"== Traces ({len(traces)} retained, {anom} anomalous) ==",
             ""]
    agg: Dict[str, List[float]] = {}
    for tdoc in traces[-last:]:
        lines += _render_one_trace(tdoc, agg)
    if len(traces) > last:
        lines.append(f"  ... {len(traces) - last} more traces "
                     "(raise --last)")
        lines.append("")
    a_rows = [[name, f"{tot * 1e3:,.3f}", str(n)]
              for name, (tot, n) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][0])]
    lines += _table("Exclusive time by span (rendered traces)",
                    ["span", "total excl ms", "count"], a_rows)
    return "\n".join(lines).rstrip() + "\n"


def render(rows: List[dict], top: int = 10, memory: bool = False,
           serve: bool = False, comms: bool = False,
           moe: bool = False, fallbacks: bool = False,
           recsys: bool = False, slo: bool = False,
           fleet: bool = False, goodput: bool = False,
           lifecycle: bool = False) -> str:
    latest = _latest_samples(rows)
    used = set()

    # -- fleet router (--fleet) first: it must claim the serve_router_*
    # series before --serve's generic serve_* catch-all slurps them ------
    serve_out: List[str] = (_fleet_section(latest, used)
                            if fleet else [])
    # -- serving (--serve) next: its histograms would otherwise be
    # swallowed by the generic slowest-events table ----------------------
    serve_out += (_serve_section(latest, used, raw_rows=rows)
                  if serve else [])
    # -- SLO burn (--slo) renders next to --serve ------------------------
    serve_out += _slo_section(latest, used) if slo else []
    # -- model lifecycle (--lifecycle) renders AFTER --slo so the burn
    # table keeps every slo_* gauge (this section only peeks them) -------
    serve_out += (_lifecycle_section(latest, used, raw_rows=rows)
                  if lifecycle else [])
    # -- training goodput (--goodput) claims the train_* ledger series
    # before the generic counter tables ----------------------------------
    serve_out += _goodput_section(latest, used) if goodput else []
    # -- comm overlap (--comms) also claims its gauges early -------------
    comms_out: List[str] = (_comms_section(latest, used) if comms else [])
    # -- MoE router health (--moe) renders next to --comms ---------------
    comms_out += _moe_section(latest, used) if moe else []
    # -- recsys embedding tiers (--recsys) next to --serve/--moe ---------
    comms_out += _recsys_section(latest, used) if recsys else []
    # -- unified degradation view (--fallbacks) ---------------------------
    comms_out += _fallbacks_section(latest, used) if fallbacks else []

    # -- slowest timing histograms ----------------------------------------
    timings = []
    for key, row in latest.items():
        name, labels = key
        if key in used:
            continue                 # --serve already rendered these
        if row.get("type") == "histogram" and row.get("count"):
            timings.append((row.get("sum", 0.0), name, labels, row))
            used.add(key)
    timings.sort(reverse=True, key=lambda t: t[0])
    t_rows = [[name, _fmt_labels(labels), str(int(r["count"])),
               f"{s:,.3f}", f"{s / r['count'] * 1e3:,.3f}"]
              for s, name, labels, r in timings[:top]]
    out = serve_out + comms_out + _table(
        f"Slowest events (top {top} by total time)",
        ["event", "labels", "count", "total s", "mean ms"], t_rows)
    if len(timings) > top:
        out.append(f"  ... {len(timings) - top} more timing series "
                   "(raise --top)\n")

    # -- compile / recompile ----------------------------------------------
    c_rows = []
    for key in sorted(latest):
        name, labels = key
        if ("compile" in name or name.startswith(("jax_", "scan_"))
                or "trace" in name) and key not in used:
            row = latest[key]
            if "value" in row:
                c_rows.append([name, _fmt_labels(labels),
                               f"{row['value']:g}"])
                used.add(key)
    out += _table("Compile / trace counters", ["metric", "labels", "value"],
                  c_rows)

    # -- comms by (op, group) ---------------------------------------------
    comm: Dict[tuple, dict] = {}
    for key, row in latest.items():
        name, labels = key
        if not name.startswith("comm_"):
            continue
        used.add(key)
        d = comm.setdefault(labels, {})
        if name == "comm_bytes_total":
            d["bytes"] = row.get("value", 0.0)
        elif name == "comm_ops_total":
            d["ops"] = row.get("value", 0.0)
        elif name == "comm_latency_seconds" and row.get("count"):
            d["lat_ms"] = row["sum"] / row["count"] * 1e3
    m_rows = [[_fmt_labels(labels), f"{d.get('ops', 0):g}",
               _fmt_bytes(d.get("bytes", 0.0)),
               f"{d.get('lat_ms', 0.0):,.3f}"]
              for labels, d in sorted(comm.items(),
                                      key=lambda kv: -kv[1].get("bytes", 0))]
    out += _table("Collectives (eager dispatch)",
                  ["op/group", "ops", "bytes", "mean dispatch ms"], m_rows)

    # -- memory (--memory) -------------------------------------------------
    if memory:
        out += _memory_section(latest, used)

    # -- everything else ---------------------------------------------------
    o_rows = []
    for key in sorted(latest):
        if key in used:
            continue
        name, labels = key
        row = latest[key]
        val = (f"count={int(row['count'])} sum={row.get('sum', 0):g}"
               if row.get("type") == "histogram"
               else f"{row.get('value', 0):g}")
        o_rows.append([name, _fmt_labels(labels), val])
    out += _table("Other metrics", ["metric", "labels", "value"], o_rows)

    if not out:
        return "(no metric samples found)"
    return "\n".join(out).rstrip() + "\n"


def render_kernels() -> str:
    """--kernels: the live ops.pallas kernel-layer inventory (flag
    matrix, dispatch status on this backend, observed fallbacks)."""
    from paddle_tpu.ops import pallas as pallas_ops
    rows = []
    for r in pallas_ops.kernels():
        flag = r["flag"] or "(shape gate)"
        if r["flag_value"] is not None:
            flag += f"={'on' if r['flag_value'] else 'off'}"
        seen = ", ".join(f"{k}:{v}" for k, v in
                         sorted(r["fallbacks_seen"].items())) or "-"
        rows.append([r["kernel"], flag,
                     "live" if r["live"] else "fallback",
                     r["fallback"], seen])
    lines = _table("ops.pallas kernel layer (this backend)",
                   ["kernel", "kill switch", "dispatch", "XLA fallback",
                    "fallbacks seen"], rows)
    lines.append("(docs/PERF_KERNELS.md; persistent fallback counts: "
                 "pallas_fallback_total in a monitor dump)")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    def int_opt(flag: str, default: int) -> Optional[int]:
        if flag not in argv:
            return default
        i = argv.index(flag)
        try:
            v = int(argv[i + 1])
        except (IndexError, ValueError):
            print(f"{flag} needs an int", file=sys.stderr)
            return None
        del argv[i:i + 2]
        return v

    top = int_opt("--top", 10)
    last = int_opt("--last", 10)
    if top is None or last is None:
        return 2
    flight = "--flight" in argv
    if flight:
        argv.remove("--flight")
    traces = "--trace" in argv
    if traces:
        argv.remove("--trace")
    memory = "--memory" in argv
    if memory:
        argv.remove("--memory")
    serve = "--serve" in argv
    if serve:
        argv.remove("--serve")
    fleet = "--fleet" in argv
    if fleet:
        argv.remove("--fleet")
    comms = "--comms" in argv
    if comms:
        argv.remove("--comms")
    moe = "--moe" in argv
    if moe:
        argv.remove("--moe")
    recsys = "--recsys" in argv
    if recsys:
        argv.remove("--recsys")
    slo = "--slo" in argv
    if slo:
        argv.remove("--slo")
    lifecycle = "--lifecycle" in argv
    if lifecycle:
        argv.remove("--lifecycle")
    goodput = "--goodput" in argv
    if goodput:
        argv.remove("--goodput")
    fallbacks = "--fallbacks" in argv
    if fallbacks:
        argv.remove("--fallbacks")
    kernels = "--kernels" in argv
    if kernels:
        argv.remove("--kernels")
    if len(argv) != (0 if kernels else 1):
        print(__doc__, file=sys.stderr)
        return 2
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    if kernels:
        print(render_kernels(), end="")
        return 0
    if flight or traces:
        import json
        try:
            with open(argv[0]) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read {argv[0]}: {e}", file=sys.stderr)
            return 2
        if traces:
            # a Tracer.dump file, a bare trace list, or a flight dump
            # whose provider attached a `traces` section
            tlist = doc if isinstance(doc, list) \
                else list(doc.get("traces") or [])
            print(render_traces(tlist, last=last), end="")
            return 0
        print(render_flight(doc, last=last), end="")
        return 0
    try:
        from paddle_tpu.monitor import load_jsonl
        rows = load_jsonl(argv[0])
    except OSError as e:
        print(f"cannot read {argv[0]}: {e}", file=sys.stderr)
        return 2
    print(render(rows, top=top, memory=memory, serve=serve, comms=comms,
                 moe=moe, fallbacks=fallbacks, recsys=recsys, slo=slo,
                 fleet=fleet, goodput=goodput, lifecycle=lifecycle),
          end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
