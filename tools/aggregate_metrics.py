"""Fold per-host registry JSONL dumps into ONE Prometheus exposition.

The multi-host story of the telemetry plane (docs/OBSERVABILITY.md
"Live telemetry plane"): every host of a multi-host run dumps its own
registry JSONL (``MetricsRegistry.dump_jsonl`` — bench records, hapi
``MonitorCallback`` streams, the recsys PS hosts). This tool rebuilds a
registry per file (newest sample per ``(name, labels)``, the
append-only contract) and merges them with
``MetricsRegistry.merge`` semantics:

- **counters** sum across hosts (and across restart segments of one
  host — the merged series stays monotonic);
- **gauges** gain a ``host=<label>`` label, so per-host values stay
  distinguishable instead of last-writer-wins clobbering;
- **histograms** merge bucket-wise; conflicting bucket boundaries are a
  hard error (exit 1), never a silent mis-merge.

The host label defaults to each file's basename stem; override per file
with ``path=hostname``.

Usage:
    python tools/aggregate_metrics.py hostA.jsonl hostB.jsonl
    python tools/aggregate_metrics.py run.jsonl=worker0 run2.jsonl=worker1 -o merged.prom
    python tools/aggregate_metrics.py --no-host-label *.jsonl

Output: the merged exposition text (stdout, or ``-o``), lint-clean per
``paddle_tpu.monitor.metrics.lint_exposition``. Classic text/plain
0.0.4 by default — safe for the node_exporter textfile collector and
any plain parser; ``--openmetrics`` switches to the OpenMetrics form
(histogram exemplars in the ``# {trace_id=...}`` suffix syntax +
``# EOF`` trailer), which classic parsers reject.

Exit code: 0 = merged, 1 = merge conflict (conflicting histogram
buckets / kind clash), 2 = usage or read errors.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

_REPO_ROOT = __file__.rsplit("/", 2)[0]
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def aggregate(specs: List[str], host_labels: bool = True):
    """Merge the given ``path[=host]`` specs into one fresh registry."""
    from paddle_tpu.monitor.metrics import (MetricsRegistry,
                                            load_registry_jsonl)
    merged = MetricsRegistry()
    for spec in specs:
        path, _, host = spec.partition("=")
        if not host:
            host = os.path.splitext(os.path.basename(path))[0]
        per_host = load_registry_jsonl(path)
        merged.merge(per_host, host=host if host_labels else None)
    return merged


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = None
    for flag in ("-o", "--out"):
        if flag in argv:
            i = argv.index(flag)
            try:
                out_path = argv[i + 1]
            except IndexError:
                print(f"{flag} needs a path", file=sys.stderr)
                return 2
            del argv[i:i + 2]
    host_labels = True
    if "--no-host-label" in argv:
        argv.remove("--no-host-label")
        host_labels = False
    openmetrics = "--openmetrics" in argv
    if openmetrics:
        argv.remove("--openmetrics")
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        merged = aggregate(argv, host_labels=host_labels)
    except OSError as e:
        print(f"cannot read input: {e}", file=sys.stderr)
        return 2
    except (ValueError, TypeError) as e:
        print(f"MERGE CONFLICT: {e}", file=sys.stderr)
        return 1
    text = merged.to_prometheus(exemplars=openmetrics)
    if openmetrics:
        text += "# EOF\n"
    from paddle_tpu.monitor.metrics import lint_exposition
    problems = lint_exposition(text)
    if problems:                      # should be unreachable: the
        for p in problems:            # emitter escapes; a hit means an
            print(f"LINT: {p}", file=sys.stderr)   # input poisoned us
        return 1
    if out_path:
        with open(out_path, "w") as f:
            f.write(text)
        print(f"wrote {out_path}: {len(merged.names())} metric(s) "
              f"from {len(argv)} host file(s)")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
