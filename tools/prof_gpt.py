"""Dev tool: attribute GPT-2 345M step time by timing ablations on the chip.

Usage: python tools/prof_gpt.py [mode ...|all]   (modes: see MODES dict)
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time

import numpy as np


def log(m):
    print(m, file=sys.stderr, flush=True)


def _sync(out):
    """Drain the dispatch pipeline with a scalar readback (works through
    the tunnel, unlike block_until_ready on wrapped Tensors)."""
    if isinstance(out, tuple):
        out = out[0]
    return float(out._data if hasattr(out, "_data") else out)


def timed(fn, args, iters=8):
    _sync(fn(*args))
    for _ in range(2):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters * 1e3


def build(B=8, S=1024, drop=0.1, remat=None, fwd_only=False,
          grads_only=False, mt=False, state_dtype="float32"):
    """remat: None | 'full' | 'dots' (selective: save dot outputs)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       GPTPretrainingCriterion, gpt2_medium)
    from paddle_tpu.optimizer import AdamW

    cfg = gpt2_medium(use_recompute=(remat is not None),
                      hidden_dropout_prob=drop, attention_dropout_prob=drop)
    paddle.seed(0)
    import paddle_tpu.distributed.fleet.utils.recompute  # noqa: F401
    # the package attr `recompute` is the *function* (star-import shadows
    # the submodule) — bind the module via sys.modules
    rc = sys.modules["paddle_tpu.distributed.fleet.utils.recompute"]
    utils_pkg = sys.modules["paddle_tpu.distributed.fleet.utils"]
    if remat == "dots":
        def sel(fn, *a, **k):
            return rc.recompute(
                fn, *a,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                **k)
        utils_pkg.recompute = sel
    else:  # undo a selective-remat patch left by an earlier mode
        utils_pkg.recompute = rc.recompute
    model = GPTForPretraining(cfg)
    model.train()
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels):
        with paddle.amp.auto_cast(level="O1"):
            return crit(layer(ids), labels)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)

    if fwd_only or grads_only:
        from paddle_tpu.core.random import trace_rng
        from paddle_tpu.core.tensor import Tensor, no_grad
        from paddle_tpu.jit.functional import bind, buffer_arrays, \
            param_arrays
        import jax.numpy as jnp
        params = param_arrays(model)
        bufs = buffer_arrays(model)

        def pure(p, i, la):
            with trace_rng(jax.random.key(0)), no_grad():
                with bind(model, p, dict(bufs)):
                    return loss_fn(model, Tensor(i),
                                   Tensor(la))._data.astype(jnp.float32)

        if fwd_only:
            f = jax.jit(pure)
            return (lambda i, la: f(params, i, la)), (ids, labels)
        g = jax.jit(jax.value_and_grad(pure))
        return (lambda i, la: g(params, i, la)), (ids, labels)

    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                weight_decay=0.01, use_multi_tensor=mt,
                state_dtype=state_dtype)
    step = TrainStep(model, loss_fn, opt)
    return step, (ids, labels)


MODES = {
    "base": dict(),
    "base_mt": dict(mt=True),
    "mt_bf16st": dict(mt=True, state_dtype="bfloat16"),
    "bf16st": dict(state_dtype="bfloat16"),
    "b16_bf16st": dict(B=16, state_dtype="bfloat16"),
    "b12_bf16st": dict(B=12, state_dtype="bfloat16"),
    "b12_mt": dict(B=12, mt=True),
    "fwdonly": dict(fwd_only=True),
    "gradsonly": dict(grads_only=True),
    "nodrop": dict(drop=0.0),
    "b12": dict(B=12),
    "b16_fullremat": dict(B=16, remat="full"),
    "b16_selremat": dict(B=16, remat="dots"),
    "b12_selremat": dict(B=12, remat="dots"),
}


def mfu(tok_s, cfg_h=1024, cfg_L=24, V=50304, S=1024):
    p_block = cfg_L * 12 * cfg_h * cfg_h
    flops_token = 6 * (p_block + V * cfg_h) + 12 * cfg_L * cfg_h * S
    return tok_s * flops_token / 197e12


def main():
    import jax
    jax.config.update("jax_default_prng_impl", "rbg")
    import paddle_tpu as paddle
    paddle.set_flags({"tpu_matmul_precision": "default"})
    which = sys.argv[1:] or ["base", "fwdonly", "gradsonly", "nodrop"]
    if which == ["all"]:
        which = list(MODES)
    for name in which:
        kw = MODES[name]
        t0 = time.perf_counter()
        step, args = build(**kw)
        ms = timed(step, args)
        B = kw.get("B", 8)
        tok = B * 1024 / (ms / 1e3)
        log(f"{name:16s} {ms:7.1f} ms/step  {tok:10,.0f} tok/s  "
            f"model-MFU={mfu(tok):.3f}  (B={B}, built+timed in "
            f"{time.perf_counter()-t0:.0f}s)")


if __name__ == "__main__":
    main()
