"""Dev tool: capture a jax.profiler trace of the GPT-2 345M train step and
print the top XLA ops by total device time."""
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import collections


def log(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    jax.config.update("jax_default_prng_impl", "rbg")
    import paddle_tpu as paddle
    paddle.set_flags({"tpu_matmul_precision": "default"})
    sys.argv = [sys.argv[0]]
    from prof_gpt import build, _sync

    step, args = build()
    _sync(step(*args))
    for _ in range(2):
        out = step(*args)
    _sync(out)

    tdir = "/tmp/gpt_trace"
    os.system(f"rm -rf {tdir}")
    with jax.profiler.trace(tdir):
        for _ in range(3):
            out = step(*args)
        _sync(out)

    paths = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
    if not paths:
        log("no trace captured")
        return
    with gzip.open(paths[0], "rt") as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    # find the XLA Ops / XLA TPU op lanes
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    op_pids = {p for p, n in pid_names.items()
               if "XLA" in n or "TensorFlow Op" in n or "/device" in n}
    import re
    tot = collections.Counter()
    cnt = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in op_pids:
            name = e.get("name", "")
            if name.startswith("jit_") or name.isdigit():
                continue                  # parent region events
            base = re.sub(r"[.\d_]+$", "", name) or name
            tot[base] += e.get("dur", 0)
            cnt[base] += 1
    log(f"lanes: {sorted(set(pid_names.values()))}")
    total_us = sum(tot.values())
    log(f"total device op time: {total_us/3/1e3:.1f} ms/step over 3 steps")
    for name, us in tot.most_common(30):
        log(f"{us/3/1e3:8.2f} ms/step ({us/total_us*100:4.1f}%)  "
            f"x{cnt[name]:4d}  {name[:100]}")


if __name__ == "__main__":
    main()
