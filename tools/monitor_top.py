"""Live terminal view of a paddle_tpu telemetry endpoint — `top` for a
serving/training process.

Polls a live ``/metrics`` endpoint (the embedded admin server,
``FLAGS_monitor_port``; docs/OBSERVABILITY.md "Live telemetry plane"),
feeds every scrape into an in-memory
``paddle_tpu.monitor.timeseries.TimeseriesRing``, and redraws one
screen of MOVEMENT per interval — rates computed from consecutive
scrapes, not the cumulative counters the raw page shows:

- **throughput**: tokens/s, requests/s by lifecycle event, decode
  dispatches/s and windowed mean decode latency;
- **pressure**: queue depth, active slots, KV pages in use, overload
  state;
- **SLO burn**: ``slo_burn_rate{slo,window}`` gauges as-is (the burn IS
  already a rate) + budget remaining;
- **training**: steps/s and the ``train_step_mfu`` gauge when the
  process publishes them.

Curses-free by design: one ANSI home+clear escape per frame (disable
with ``--no-clear`` for dumb terminals / piped output), so it runs over
any ssh session. Everything is computed from the scrape text — the tool
never imports jax and works against any process exposing the format.

With ``--fleet``, point it at a fleet federator
(``FLAGS_fleet_monitor_port``; docs/OBSERVABILITY.md "Fleet
observability") instead of a single process: the federated page is
host-labelled, so the frame gains a **per-replica pane** — one row per
replica with tokens/s, queue depth, KV pages in use and shed/overload
state, plus the fleet totals the summary rows already show.

Usage:
    python tools/monitor_top.py http://127.0.0.1:9090 [--interval 1.0]
    python tools/monitor_top.py http://host:port/metrics --iterations 30
    python tools/monitor_top.py --once http://127.0.0.1:9090
    python tools/monitor_top.py --fleet http://127.0.0.1:9091

Exit code: 0 (including Ctrl-C), 2 on usage errors. Scrape failures
render as a banner and the loop keeps trying — a restarting server must
not kill the operator's view.
"""

from __future__ import annotations

import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional

_REPO_ROOT = __file__.rsplit("/", 2)[0]
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

#: trailing window (seconds) for every rate shown
RATE_WINDOW_S = 30.0

_CLEAR = "\x1b[H\x1b[2J"


def _fmt(v: Optional[float], fmt: str = "{:,.1f}",
         none: str = "-") -> str:
    return fmt.format(v) if v is not None else none


def scrape(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", "replace")


def _fleet_hosts(ring) -> List[str]:
    """Distinct host labels on the federated serving series."""
    hosts = set()
    for name in ("serve_queue_depth", "serve_tokens_generated_total",
                 "serve_requests_total"):
        for labels in ring.label_sets(name):
            h = labels.get("host")
            if h is not None:
                hosts.add(h)
    return sorted(hosts)


def render_fleet_pane(ring,
                      window_s: Optional[float] = None) -> List[str]:
    """Per-replica rows off a federated (host-labelled) page. Pure
    function of the ring — tests drive it without any HTTP. Empty when
    the page carries no host labels (not a federator)."""
    W = RATE_WINDOW_S if window_s is None else window_s
    hosts = _fleet_hosts(ring)
    if not hosts:
        return []
    lines = ["", "replica      tokens/s    queue   kv pages   "
                 "shed/s   state"]
    for h in hosts:
        tok = ring.rate("serve_tokens_generated_total", W, host=h)
        q = ring.latest("serve_queue_depth", host=h)
        pages = ring.latest("serve_kv_pages_in_use", host=h)
        shed = ring.rate("serve_requests_total", W, host=h,
                         event="shed")
        over = ring.latest("serve_overload", host=h)
        state = ("OVERLOADED" if over else "ok") \
            if over is not None else "-"
        if shed:
            state += " shedding"
        lines.append(f"{h:<12} {_fmt(tok):>9}  {_fmt(q, '{:,.0f}'):>6}"
                     f"  {_fmt(pages, '{:,.0f}'):>9}"
                     f"  {_fmt(shed, '{:,.2f}'):>7}   {state}")
    ready = ring.latest("fleet_replicas", state="ready")
    unreach = ring.latest("fleet_replicas", state="unreachable")
    if ready is not None or unreach is not None:
        lines.append(f"fleet     ready {_fmt(ready, '{:,.0f}')}"
                     f"   unreachable {_fmt(unreach, '{:,.0f}')}")
    return lines


def render_frame(ring, url: str, now: Optional[float] = None,
                 error: Optional[str] = None,
                 fleet: bool = False) -> str:
    """One screen of movement from the ring's history. Pure function of
    the ring — tests drive it without any HTTP. ``fleet=True`` appends
    the per-replica pane (host-labelled federator pages)."""
    W = RATE_WINDOW_S
    lines: List[str] = []
    ts = time.strftime("%H:%M:%S",
                       time.localtime(now if now is not None else
                                      time.time()))
    lines.append(f"paddle_tpu monitor_top — {url} — {ts} "
                 f"(rates over {W:g}s, {ring.snapshots_taken} scrapes)")
    if error:
        lines.append(f"!! scrape failed: {error}")
    lines.append("")

    # -- serving throughput -------------------------------------------------
    tok_s = ring.rate("serve_tokens_generated_total", W)
    dec_s = ring.rate("serve_decode_step_seconds_count", W)
    dec_sum = ring.delta("serve_decode_step_seconds_sum", W)
    dec_cnt = ring.delta("serve_decode_step_seconds_count", W)
    dec_ms = (dec_sum / dec_cnt * 1e3
              if dec_sum is not None and dec_cnt else None)
    lines.append(f"serving   tokens/s {_fmt(tok_s):>10}   "
                 f"decode/s {_fmt(dec_s):>8}   "
                 f"decode mean {_fmt(dec_ms, '{:,.2f}')} ms")
    ev_bits = []
    for labels in ring.label_sets("serve_requests_total"):
        r = ring.rate("serve_requests_total", W, **labels)
        if r:
            ev_bits.append(f"{labels.get('event', '?')} {r:,.2f}/s")
    if ev_bits:
        lines.append("requests  " + "   ".join(sorted(ev_bits)))

    # -- pressure -----------------------------------------------------------
    q = ring.latest("serve_queue_depth")
    slots = ring.latest("serve_active_slots")
    pages = ring.latest("serve_kv_pages_in_use")
    over = ring.latest("serve_overload")
    if any(v is not None for v in (q, slots, pages, over)):
        state = ("OVERLOADED" if over else "normal") \
            if over is not None else "-"
        lines.append(f"pressure  queue {_fmt(q, '{:,.0f}'):>6}   "
                     f"slots {_fmt(slots, '{:,.0f}'):>4}   "
                     f"kv pages {_fmt(pages, '{:,.0f}'):>6}   "
                     f"state {state}")

    # -- SLO burn (already a rate: show the gauge) --------------------------
    burn_rows = []
    for labels in ring.label_sets("slo_burn_rate"):
        v = ring.latest("slo_burn_rate", **labels)
        if v is not None:
            burn_rows.append((labels.get("slo", "?"),
                              labels.get("window", "?"), v))
    if burn_rows:
        lines.append("")
        lines.append("SLO burn  (1.0 = spending exactly the budget)")
        by_slo = {}
        for slo, window, v in sorted(burn_rows):
            by_slo.setdefault(slo, []).append(f"{window}={v:,.2f}")
        for slo, cells in sorted(by_slo.items()):
            rem = ring.latest("slo_error_budget_remaining", slo=slo)
            rem_s = f"   budget left {_fmt(rem, '{:,.3f}')}" \
                if rem is not None else ""
            lines.append(f"  {slo:<24} " + "  ".join(cells) + rem_s)

    # -- training -----------------------------------------------------------
    t_rows = []
    for labels in ring.label_sets("train_step_steps_total"):
        r = ring.rate("train_step_steps_total", W, **labels)
        if r:
            mfu = ring.latest("train_step_mfu", **labels)
            t_rows.append(f"{labels.get('kind', '?')} "
                          f"{r:,.2f} steps/s"
                          + (f" mfu {mfu:.3f}" if mfu is not None
                             else ""))
    if t_rows:
        lines.append("")
        lines.append("training  " + "   ".join(sorted(t_rows)))

    # -- training goodput (FLAGS_train_goodput) -----------------------------
    gp = ring.latest("train_goodput_pct")
    if gp is not None:
        lines.append("")
        lines.append(f"goodput   {gp:5.1f}% productive")
        bad_bits = []
        for labels in ring.label_sets("train_badput_seconds_total"):
            r = ring.rate("train_badput_seconds_total", W, **labels)
            if r:
                # seconds-per-second of badput: 0.25 = a quarter of
                # wall-clock going to this bucket over the window
                bad_bits.append(f"{labels.get('bucket', '?')} {r:,.2f}")
        if bad_bits:
            lines.append("badput/s  " + "   ".join(sorted(bad_bits)))
        # top-offender layers by grad norm (FLAGS_train_health_every)
        layer_rows = []
        for labels in ring.label_sets("train_layer_grad_norm"):
            v = ring.latest("train_layer_grad_norm", **labels)
            if v is not None:
                layer_rows.append((v, labels.get("layer", "?")))
        if layer_rows:
            layer_rows.sort(reverse=True)
            cells = []
            for v, layer in layer_rows[:4]:
                u = ring.latest("train_layer_update_ratio", layer=layer)
                cells.append(f"{layer} |g|={v:,.3g}"
                             + (f" u={u:,.1e}" if u is not None else ""))
            lines.append("layers    " + "   ".join(cells))

    if fleet:
        lines.extend(render_fleet_pane(ring))

    if ring.snapshots_taken < 2:
        lines.append("")
        lines.append("(rates need two scrapes — hold on...)")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    def fopt(flag: str, default: float) -> Optional[float]:
        if flag not in argv:
            return default
        i = argv.index(flag)
        try:
            v = float(argv[i + 1])
        except (IndexError, ValueError):
            print(f"{flag} needs a number", file=sys.stderr)
            return None
        del argv[i:i + 2]
        return v

    interval = fopt("--interval", 1.0)
    iterations = fopt("--iterations", 0.0)
    if interval is None or iterations is None:
        return 2
    once = "--once" in argv
    if once:
        argv.remove("--once")
    no_clear = "--no-clear" in argv
    if no_clear:
        argv.remove("--no-clear")
    fleet = "--fleet" in argv
    if fleet:
        argv.remove("--fleet")
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    url = argv[0]
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    if not url.endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"

    from paddle_tpu.monitor.timeseries import (TimeseriesRing,
                                               parse_prometheus)
    ring = TimeseriesRing(capacity=max(
        16, int(600 / max(interval, 0.1))))
    n = 0
    try:
        while True:
            err = None
            try:
                ring.ingest_rows(parse_prometheus(scrape(url)))
            except (urllib.error.URLError, OSError, ValueError) as e:
                err = str(e)
            frame = render_frame(ring, url, error=err, fleet=fleet)
            sys.stdout.write(frame if no_clear else _CLEAR + frame)
            sys.stdout.flush()
            n += 1
            if once or (iterations and n >= iterations):
                return 0
            time.sleep(max(interval, 0.05))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
