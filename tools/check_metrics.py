"""Metric-name drift gate: every registry metric the source emits must
be documented in docs/OBSERVABILITY.md, and every documented metric
must still exist in the source.

Telemetry names are an API: dashboards, alerts and the bench gate key
on them, and a silent rename (or an undocumented addition) breaks
consumers without failing any test. This tool walks the python source
for registry emit sites — ``.counter("name"...)``, ``.gauge(`` and
``.histogram(`` calls (including the ``"a" if cond else "b"``
conditional-name form) — and diffs the emitted set against the
**Metric inventory** table of docs/OBSERVABILITY.md. Run as a tier-1
test (tests/test_check_metrics.py), so CI enforces the sync.

Usage:
    python tools/check_metrics.py [--root /path/to/repo]

Exit code: 0 = in sync, 1 = drift (undocumented or documented-but-gone
metrics listed), 2 = usage error.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

#: source roots scanned for emit sites, relative to the repo root
SOURCE_ROOTS = ("paddle_tpu", "bench.py")

#: the doc that is the single source of truth for metric names
DOC_PATH = os.path.join("docs", "OBSERVABILITY.md")

#: section marker in the doc: names are collected from backticked
#: tokens between this heading and the next `## ` heading
DOC_SECTION = "## Metric inventory"

_EMIT_RE = re.compile(r"\.(counter|gauge|histogram)\s*\(")
#: escape hatch for computed metric names the literal scanner cannot
#: see: a `# emits-metrics: a, b, c` comment next to the emit site
#: declares them (and the drift gate then also demands they stay
#: documented)
_ANNOT_RE = re.compile(r"#\s*emits-metrics:[ \t]*([a-z0-9_, \t]+)")
#: metric-name shape: lowercase snake_case with >= 1 underscore (help
#: strings are prose — spaces keep them out; single words without an
#: underscore are never metric names here)
_NAME_RE = re.compile(r'["\']([a-z][a-z0-9]*(?:_[a-z0-9]+)+)["\']')


def _first_arg_chunk(text: str, start: int) -> str:
    """The first-argument region of a call starting at ``start`` (the
    char after the open paren): up to the first comma at paren depth 0.
    Captures plain literals AND conditional-name expressions like
    ``"a" if warm else "b"``."""
    depth = 0
    for i in range(start, min(len(text), start + 400)):
        c = text[i]
        if c in "([{":
            depth += 1
        elif c in ")]}":
            if depth == 0:
                return text[start:i]
            depth -= 1
        elif c == "," and depth == 0:
            return text[start:i]
    return text[start:start + 400]


def emitted_metrics(root: str) -> Dict[str, Set[str]]:
    """{metric_name: {file:line, ...}} for every registry emit site
    under the source roots. Dynamic names that are not string literals
    in the first argument cannot be scanned — keep names literal (the
    conditional two-literal form is supported)."""
    out: Dict[str, Set[str]] = {}
    files: List[str] = []
    for src in SOURCE_ROOTS:
        path = os.path.join(root, src)
        if os.path.isfile(path):
            files.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files += [os.path.join(dirpath, f) for f in filenames
                      if f.endswith(".py")]
    for path in sorted(files):
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        for m in _EMIT_RE.finditer(text):
            chunk = _first_arg_chunk(text, m.end())
            for name in _NAME_RE.findall(chunk):
                line = text.count("\n", 0, m.start()) + 1
                out.setdefault(name, set()).add(f"{rel}:{line}")
        for m in _ANNOT_RE.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            for name in re.split(r"[,\s]+", m.group(1).strip()):
                if name:
                    out.setdefault(name, set()).add(
                        f"{rel}:{line} (annotation)")
    return out


def documented_metrics(root: str) -> Set[str]:
    """Backticked metric names inside the doc's Metric inventory
    section (up to the next ``## `` heading)."""
    path = os.path.join(root, DOC_PATH)
    with open(path) as f:
        text = f.read()
    idx = text.find(DOC_SECTION)
    if idx < 0:
        raise ValueError(
            f"{DOC_PATH} has no {DOC_SECTION!r} section — the drift "
            "gate needs it as the single source of documented names")
    section = text[idx + len(DOC_SECTION):]
    nxt = section.find("\n## ")
    if nxt >= 0:
        section = section[:nxt]
    return {m.group(1)
            for m in re.finditer(r"`([a-z][a-z0-9]*(?:_[a-z0-9]+)+)`",
                                 section)}


def check(root: str) -> Tuple[List[str], Dict[str, Set[str]], Set[str]]:
    """Returns (problems, emitted, documented)."""
    emitted = emitted_metrics(root)
    documented = documented_metrics(root)
    problems: List[str] = []
    for name in sorted(set(emitted) - documented):
        sites = ", ".join(sorted(emitted[name])[:3])
        problems.append(
            f"UNDOCUMENTED metric {name!r} (emitted at {sites}) — add "
            f"it to the {DOC_SECTION!r} table in {DOC_PATH}")
    for name in sorted(documented - set(emitted)):
        problems.append(
            f"DOCUMENTED-BUT-GONE metric {name!r} — no emit site found "
            f"in the source; remove it from {DOC_PATH} (or restore the "
            "emitter)")
    return problems, emitted, documented


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "--root" in argv:
        i = argv.index("--root")
        try:
            root = argv[i + 1]
        except IndexError:
            print("--root needs a path", file=sys.stderr)
            return 2
        del argv[i:i + 2]
    if argv:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        problems, emitted, documented = check(root)
    except (OSError, ValueError) as e:
        print(f"check_metrics: {e}", file=sys.stderr)
        return 2
    if problems:
        print("METRIC DRIFT:")
        for p in problems:
            print("  " + p)
        return 1
    print(f"metric inventory in sync: {len(emitted)} emitted names, "
          f"{len(documented)} documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
