"""SGD / Momentum (reference: operators/optimizers/sgd_op.cc, momentum_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._use_multi_tensor = use_multi_tensor

    def _init_slot(self, param):
        return ()

    def _update(self, param, grad, slots, lr, t):
        return param.astype(jnp.float32) - lr * grad.astype(jnp.float32), ()


class Momentum(Optimizer):
    """Heavy-ball / Nesterov momentum, with optional LARS-style local scaling
    handled by Lars* subclasses in the reference; use_nesterov matches the
    reference flag (reference: python/paddle/optimizer/momentum.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self.momentum = momentum
        self.use_nesterov = use_nesterov
        self.rescale_grad = rescale_grad
        self._use_multi_tensor = use_multi_tensor

    def _init_slot(self, param):
        return (jnp.zeros(param.shape, jnp.float32),)

    def _update(self, param, grad, slots, lr, t):
        (vel,) = slots
        g = grad.astype(jnp.float32) * self.rescale_grad
        vel = self.momentum * vel + g
        if self.use_nesterov:
            delta = g + self.momentum * vel
        else:
            delta = vel
        return param.astype(jnp.float32) - lr * delta, (vel,)
