"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByGlobalNorm/Norm/Value).

Clips operate on grad pytrees (dicts of arrays) so they compose with both the
eager step() path and jitted functional updates; hybrid-parallel variants
psum the global norm across model-parallel axes (see distributed)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class GradClipBase:
    def __call__(self, grads: dict) -> dict:
        raise NotImplementedError


class ClipGradByValue(GradClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, grads):
        return {k: jnp.clip(g, self.min, self.max) for k, g in grads.items()}


class ClipGradByNorm(GradClipBase):
    """Per-tensor L2 norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads):
        def _clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return {k: _clip(g) for k, g in grads.items()}


class ClipGradByGlobalNorm(GradClipBase):
    """Global L2 norm clip across all grads (the hybrid-parallel optimizer
    wraps this to psum the squared norm over tp/pp groups — reference:
    fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        # optional hook: called with the local squared-norm, returns global
        self.norm_reduce_fn = None

    def __call__(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads.values())
        if self.norm_reduce_fn is not None:
            sq = self.norm_reduce_fn(sq)
        gnorm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return {k: (g * scale).astype(g.dtype) for k, g in grads.items()}
