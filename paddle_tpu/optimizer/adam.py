"""Adam-family optimizers (reference: operators/optimizers/adam_op.cc,
python/paddle/optimizer/adam.py, adamw.py, lamb.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["Adam", "AdamW", "Lamb", "Adamax", "Adadelta", "Adagrad", "RMSProp"]


class Adam(Optimizer):
    """``state_dtype="bfloat16"`` keeps the m/v slots in bf16 (compute
    stays f32): halves optimizer-state HBM traffic AND footprint — the
    TPU-native analogue of the reference's fused low-memory Adam variants
    (operators/optimizers/adam_op.cu:1 multi-precision paths). bf16's
    8-bit mantissa costs <0.5% relative error on the denominator; fine
    for pretraining (loss-parity covered in tests/test_optimizer.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, state_dtype="float32", name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self._use_multi_tensor = use_multi_tensor
        self._state_dtype = jnp.dtype(state_dtype)

    def _init_slot(self, param):
        m = jnp.zeros(param.shape, self._state_dtype)
        v = jnp.zeros(param.shape, self._state_dtype)
        return (m, v)

    def _update(self, param, grad, slots, lr, t):
        m, v = slots
        g = grad.astype(jnp.float32)
        m = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
        v = self.beta2 * v.astype(jnp.float32) \
            + (1 - self.beta2) * jnp.square(g)
        t_f = jnp.asarray(t, jnp.float32)
        bc1 = 1 - jnp.power(self.beta1, t_f)
        bc2 = 1 - jnp.power(self.beta2, t_f)
        lr_t = lr * jnp.sqrt(bc2) / bc1
        new_param = param.astype(jnp.float32) - lr_t * m / (jnp.sqrt(v) + self.epsilon)
        return new_param, (m.astype(self._state_dtype),
                           v.astype(self._state_dtype))


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, state_dtype="float32", name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         use_multi_tensor, state_dtype, name)
        self._wd_coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else getattr(weight_decay, "coeff", 0.0)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        if apply_decay_param_fun is not None:
            # per-name decay decisions don't batch over stacked groups
            self._use_multi_tensor = False

    def _update(self, param, grad, slots, lr, t):
        new_param, new_slots = super()._update(param, grad, slots, lr, t)
        if self._wd_coeff:
            new_param = new_param - lr * self._wd_coeff * param.astype(jnp.float32)
        return new_param, new_slots

    def apply_gradients(self, params, grads, state, lr=None, step=None):
        """Respect apply_decay_param_fun by name (paddle semantics)."""
        if self._apply_decay_param_fun is None:
            return super().apply_gradients(params, grads, state, lr, step)
        saved = self._wd_coeff
        new_params, new_state = {}, {}
        if lr is None:
            lr = self.get_lr()
        if step is None:
            step = self._step_count + 1
        if self._grad_clip is not None:
            grads = self._grad_clip(grads)
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k], new_state[k] = p, state[k]
                continue
            self._wd_coeff = saved if self._apply_decay_param_fun(k) else 0.0
            np_, ns = self._update(p, g, state[k], lr, step)
            new_params[k] = np_.astype(p.dtype)
            new_state[k] = ns
        self._wd_coeff = saved
        return new_params, new_state


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.cc."""

    _mt_fusable = False   # per-param trust ratio (norms) can't batch

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.wd = lamb_weight_decay
        self.exclude_fn = exclude_from_weight_decay_fn

    def _init_slot(self, param):
        return (jnp.zeros(param.shape, jnp.float32),
                jnp.zeros(param.shape, jnp.float32))

    def _update(self, param, grad, slots, lr, t):
        m, v = slots
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        t_f = jnp.asarray(t, jnp.float32)
        m_hat = m / (1 - jnp.power(self.beta1, t_f))
        v_hat = v / (1 - jnp.power(self.beta2, t_f))
        r = m_hat / (jnp.sqrt(v_hat) + self.epsilon) + self.wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p32 - lr * trust * r, (m, v)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slot(self, param):
        return (jnp.zeros(param.shape, jnp.float32),
                jnp.zeros(param.shape, jnp.float32))

    def _update(self, param, grad, slots, lr, t):
        m, u = slots
        g = grad.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        t_f = jnp.asarray(t, jnp.float32)
        lr_t = lr / (1 - jnp.power(self.beta1, t_f))
        return param.astype(jnp.float32) - lr_t * m / (u + self.epsilon), (m, u)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.rho, self.epsilon = rho, epsilon

    def _init_slot(self, param):
        return (jnp.zeros(param.shape, jnp.float32),
                jnp.zeros(param.shape, jnp.float32))

    def _update(self, param, grad, slots, lr, t):
        avg_sq, avg_upd = slots
        g = grad.astype(jnp.float32)
        avg_sq = self.rho * avg_sq + (1 - self.rho) * jnp.square(g)
        upd = jnp.sqrt(avg_upd + self.epsilon) / jnp.sqrt(avg_sq + self.epsilon) * g
        avg_upd = self.rho * avg_upd + (1 - self.rho) * jnp.square(upd)
        return param.astype(jnp.float32) - lr * upd, (avg_sq, avg_upd)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _init_slot(self, param):
        return (jnp.full(param.shape, self.init_acc, jnp.float32),)

    def _update(self, param, grad, slots, lr, t):
        (acc,) = slots
        g = grad.astype(jnp.float32)
        acc = acc + jnp.square(g)
        return param.astype(jnp.float32) - lr * g / (jnp.sqrt(acc) + self.epsilon), (acc,)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def _init_slot(self, param):
        ms = jnp.zeros(param.shape, jnp.float32)
        mom = jnp.zeros(param.shape, jnp.float32)
        mg = jnp.zeros(param.shape, jnp.float32)
        return (ms, mom, mg)

    def _update(self, param, grad, slots, lr, t):
        ms, mom, mg = slots
        g = grad.astype(jnp.float32)
        ms = self.rho * ms + (1 - self.rho) * jnp.square(g)
        if self.centered:
            mg = self.rho * mg + (1 - self.rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
        else:
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum * mom + lr * g / denom
        return param.astype(jnp.float32) - mom, (ms, mom, mg)
