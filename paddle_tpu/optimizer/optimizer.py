"""Optimizer base.

Redesign of the reference's optimizer family
(reference: python/paddle/optimizer/optimizer.py:49 + C++ kernels
operators/optimizers/*).

Architecture: each optimizer defines a **pure update rule**
``_init_slot(param) -> slots`` and ``_update(param, grad, slots, lr, t) ->
(new_param, new_slots)``. Two consumers:

- Eager ``step()``: gathers all (param, grad) pairs and applies ONE jitted
  fused multi-tensor update over the whole param dict (the TPU answer to the
  reference's fused `merged_adam`/multi_tensor kernels) with buffer donation.
- Functional training (jit/distributed): ``init_state`` + ``apply_gradients``
  run inside the caller's jitted step, so the update fuses into the step's
  XLA program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from .clip import GradClipBase
from .lr import LRScheduler

__all__ = ["Optimizer"]


class L2Decay:
    """Coupled L2 regularizer (reference: fluid/regularizer.py L2Decay)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    # subclasses override
    _hyper_defaults: Dict[str, float] = {}
    #: elementwise update rules fuse over stacked param groups; rules with
    #: per-param reductions (Lamb's trust ratio) must opt out
    _mt_fusable = True

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._name = name
        # weight decay: float => L2Decay (coupled, reference semantics);
        # AdamW overrides with decoupled decay.
        if isinstance(weight_decay, (int, float)):
            self.regularization = L2Decay(weight_decay)
        else:
            self.regularization = weight_decay
        self._accumulators: Dict[int, Any] = {}  # id(param) -> slots pytree
        self._step_count = 0
        self._fused_step_cache: Dict[Any, Callable] = {}
        self._use_multi_tensor = False

    # ------------------------------------------------------------------
    # LR plumbing
    # ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # ------------------------------------------------------------------
    # Pure rule API (overridden by subclasses)
    # ------------------------------------------------------------------
    def _init_slot(self, param: jnp.ndarray):
        """Return the per-param slot pytree (e.g. (m, v) for Adam)."""
        return ()

    def _update(self, param, grad, slots, lr, t):
        """Pure single-param update. Returns (new_param, new_slots)."""
        raise NotImplementedError

    def _coupled_decay(self, param, grad):
        if isinstance(self.regularization, L2Decay) and self.regularization.coeff:
            return grad + self.regularization.coeff * param
        if isinstance(self.regularization, L1Decay) and self.regularization.coeff:
            return grad + self.regularization.coeff * jnp.sign(param)
        return grad

    # ------------------------------------------------------------------
    # Functional API (used by jitted trainers — runs under tracing)
    # ------------------------------------------------------------------
    def init_state(self, params: Dict[str, jnp.ndarray]):
        if self._use_multi_tensor and self._mt_fusable:
            # multi-tensor mode (reference: use_multi_tensor /
            # merged_adam multi-tensor CUDA kernels,
            # operators/optimizers/merged_adam_op.cc): group params by
            # (shape, dtype), keep slots STACKED [N, *shape] per group —
            # the update runs as ~a dozen large fused kernels instead of
            # one tiny fusion per parameter (a ~300-launch, ~30 ms/step
            # overhead on GPT-2 345M, see tools/trace_gpt.py)
            groups: Dict[Any, List[str]] = {}
            for k in sorted(params):
                gid = (tuple(params[k].shape), str(params[k].dtype))
                groups.setdefault(gid, []).append(k)
            # the name->group map is DERIVED state (deterministic given the
            # param dict) kept on the instance — jit-traced opt_state must
            # hold only arrays
            self._mt_groups = {f"mt{i}": names for i, (_, names) in
                               enumerate(sorted(groups.items(),
                                                key=lambda kv: repr(kv[0])))}
            slots = {gk: self._init_slot(
                jnp.stack([params[k] for k in names]))
                for gk, names in self._mt_groups.items()}
            return {"__mt__": slots}
        return {k: self._init_slot(p) for k, p in params.items()}

    def _apply_gradients_mt(self, params, grads, state, lr, step):
        """Stacked multi-tensor update (state from the __mt__ layout)."""
        if lr is None:
            lr = self.get_lr()
        if step is None:
            step = self._step_count + 1
        if self._grad_clip is not None:
            grads = self._grad_clip(grads)
        gmap = self._mt_groups
        slots = state["__mt__"]
        grouped = {k for names in gmap.values() for k in names}
        if set(params) != grouped:
            extra = sorted(set(params) - grouped)[:3]
            gone = sorted(grouped - set(params))[:3]
            raise ValueError(
                "use_multi_tensor=True: the parameter dict no longer "
                "matches the groups built at init_state (new: "
                f"{extra}, missing: {gone}); call init_state again after "
                "changing the parameter set")
        new_params, new_slots = {}, {}
        for gk, names in gmap.items():
            missing = [k for k in names if grads.get(k) is None]
            if missing:
                raise ValueError(
                    "use_multi_tensor=True needs a gradient for every "
                    f"parameter (none for {missing[:3]}); construct the "
                    "optimizer with use_multi_tensor=False for partially-"
                    "frozen parameter sets")
            p_s = jnp.stack([params[k] for k in names])
            g_s = jnp.stack([grads[k] for k in names])
            if self._multi_precision:
                g_s = g_s.astype(jnp.float32)
            g_s = self._coupled_decay(p_s, g_s)
            np_s, ns = self._update(p_s, g_s, slots[gk], lr, step)
            np_s = np_s.astype(params[names[0]].dtype)
            new_slots[gk] = ns
            for i, k in enumerate(names):
                new_params[k] = np_s[i]
        return new_params, {"__mt__": new_slots}

    def apply_gradients(self, params: Dict[str, jnp.ndarray],
                        grads: Dict[str, jnp.ndarray], state, lr=None, step=None):
        """Pure fused update over a param dict. Returns (params, state)."""
        if isinstance(state, dict) and "__mt__" in state:
            return self._apply_gradients_mt(params, grads, state, lr, step)
        if lr is None:
            lr = self.get_lr()
        if step is None:
            step = self._step_count + 1
        if self._grad_clip is not None:
            grads = self._grad_clip(grads)
        new_params, new_state = {}, {}
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k] = p
                new_state[k] = state[k]
                continue
            g = self._coupled_decay(p, g.astype(jnp.float32) if
                                    self._multi_precision else g)
            np_, ns = self._update(p, g, state[k], lr, step)
            new_params[k] = np_.astype(p.dtype)
            new_state[k] = ns
        return new_params, new_state

    # ------------------------------------------------------------------
    # Eager API (paddle UX)
    # ------------------------------------------------------------------
    def _ensure_params(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters; "
                             "pass parameters=layer.parameters()")
        return [p for p in self._parameter_list if isinstance(p, Parameter) or
                isinstance(p, Tensor)]

    def step(self):
        params = self._ensure_params()
        live = [(i, p) for i, p in enumerate(params)
                if p.grad is not None and getattr(p, "trainable", True)]
        if not live:
            return
        self._step_count += 1
        keys = [str(i) for i, _ in live]
        param_arrays = {k: p._data for k, (_, p) in zip(keys, live)}
        grad_arrays = {k: p.grad._data for k, (_, p) in zip(keys, live)}

        # slot init (eager, once per param)
        for k, (_, p) in zip(keys, live):
            if id(p) not in self._accumulators:
                self._accumulators[id(p)] = self._init_slot(p._data)
        state = {k: self._accumulators[id(p)] for k, (_, p) in zip(keys, live)}

        lr = jnp.asarray(self.get_lr(), jnp.float32)
        t = jnp.asarray(self._step_count, jnp.int32)

        cache_key = tuple(
            (k, p._data.shape, str(p._data.dtype)) for k, (_, p) in zip(keys, live))
        fused = self._fused_step_cache.get(cache_key)
        if fused is None:
            def _fused(params_d, grads_d, state_d, lr_s, t_s):
                return self.apply_gradients(params_d, grads_d, state_d, lr_s, t_s)
            fused = jax.jit(_fused, donate_argnums=(0, 2))
            self._fused_step_cache[cache_key] = fused

        new_params, new_state = fused(param_arrays, grad_arrays, state, lr, t)
        for k, (_, p) in zip(keys, live):
            p._data = new_params[k]
            self._accumulators[id(p)] = new_state[k]

    # reference's minimize(): compute backward then step; under an active
    # static.program_guard it instead ATTACHES this optimizer to the
    # recording program (the reference appends backward+optimizer ops to
    # the program the same way)
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..core.tensor import _static_recorders
        if _static_recorders:
            prog = _static_recorders[-1]
            prog._optimizer = self
            prog._loss = loss
            return None, None
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        for p in self._ensure_params():
            p.clear_grad()

    clear_gradients = clear_grad

    # ------------------------------------------------------------------
    # State persistence
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        import numpy as np
        out: Dict[str, Any] = {"_step_count": self._step_count}
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                slots = self._accumulators.get(id(p))
                if slots is None:
                    continue
                flat, _ = jax.tree_util.tree_flatten(slots)
                for j, leaf in enumerate(flat):
                    out[f"param{i}_slot{j}"] = np.asarray(leaf)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state: Dict[str, Any]):
        self._step_count = int(state.get("_step_count", 0))
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                slots = self._accumulators.get(id(p))
                if slots is None:
                    slots = self._init_slot(p._data)
                flat, treedef = jax.tree_util.tree_flatten(slots)
                loaded = []
                for j, leaf in enumerate(flat):
                    key = f"param{i}_slot{j}"
                    loaded.append(jnp.asarray(state[key]) if key in state else leaf)
                self._accumulators[id(p)] = jax.tree_util.tree_unflatten(treedef, loaded)
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
