"""paddle_tpu.optimizer (reference surface: python/paddle/optimizer/)."""

from . import lr  # noqa: F401
from .adam import Adam, Adadelta, Adagrad, Adamax, AdamW, Lamb, RMSProp  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .optimizer import L1Decay, L2Decay, Optimizer  # noqa: F401
from .sgd import SGD, Momentum  # noqa: F401
