"""paddle.onnx surface.

reference parity: python/paddle/onnx/export.py — a thin wrapper over a
program->ONNX converter (paddle2onnx in the reference).

TPU-native: `export` traces the model to a jaxpr and emits a REAL
`.onnx` ModelProto (paddle_tpu.onnx_export: hand-written protobuf wire
encoder + primitive mappers — verified by the bundled decoder/numpy
runtime, since no onnx package ships in this image). Models using
primitives without a mapping fall back to the StableHLO artifact set
(jit.save) with a loud warning naming the unsupported primitive — a
partial export is never silently wrong.
"""

from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs):
    """Export ``layer`` (reference: onnx/export.py).

    Returns the ``.onnx`` path on success. On unsupported models, writes
    the StableHLO artifact set instead and returns ``path + ".mlir"``
    (with a warning naming the unsupported primitive).
    """
    from .onnx_export import UnsupportedOnnxExport
    from .onnx_export import export as real_export

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec (static shapes)")
    if configs:
        raise ValueError(
            f"unsupported ONNX-specific options: {sorted(configs)}")
    try:
        return real_export(layer, path, input_spec=input_spec,
                           opset_version=opset_version)
    except UnsupportedOnnxExport as e:
        from .jit.to_static import save as jit_save
        jit_save(layer, path, input_spec=input_spec)
        warnings.warn(
            f"ONNX export unsupported for this model ({e}); wrote the "
            f"StableHLO artifact set instead: {path}.mlir "
            "(+ .jaxexport/.pdiparams) — the XLA-native interchange "
            "format that StableHLO consumers (IREE, XLA AOT) ingest.",
            stacklevel=2)
        return path + ".mlir"
