"""paddle.onnx surface.

reference parity: python/paddle/onnx/export.py — a thin wrapper delegating
to the external `paddle2onnx` converter over a jit-saved inference model.

TPU-native reality: the portable interchange format for XLA-compiled
models is StableHLO, not ONNX — `export` produces the jit.save artifact
set (.mlir StableHLO text + .jaxexport serialized executable + params),
which StableHLO consumers (IREE, XLA AOT, onnx-mlir's StableHLO importer)
ingest directly. No .onnx protobuf is written (no converter is shipped);
the function says so loudly via a warning and its return value names the
actual artifacts, so nothing downstream can mistake the output for ONNX.
"""

from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    """Export ``layer`` for interchange (reference: onnx/export.py).

    Writes the StableHLO artifact set at ``path`` (same as jit.save) and
    returns the ``path + ".mlir"`` it actually wrote. ``opset_version``
    and ONNX-specific ``configs`` do not apply to StableHLO and are
    rejected when set to non-defaults, rather than silently dropped.
    """
    from .jit.to_static import save as jit_save

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec (static shapes)")
    if opset_version != 9:
        raise ValueError(
            f"opset_version={opset_version} has no meaning for the "
            "StableHLO export this framework produces; omit it")
    if configs:
        raise ValueError(
            f"unsupported ONNX-specific options: {sorted(configs)} — the "
            "export is StableHLO (.mlir/.jaxexport), not an .onnx protobuf")
    jit_save(layer, path, input_spec=input_spec)
    warnings.warn(
        "paddle_tpu exports StableHLO, the XLA-native interchange format: "
        f"wrote {path}.mlir (+ .jaxexport/.pdiparams). No .onnx protobuf "
        "is produced; use a StableHLO->ONNX converter if you need one.",
        stacklevel=2)
    return path + ".mlir"
