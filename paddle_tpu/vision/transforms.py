"""Vision transforms (reference: python/paddle/vision/transforms/) — numpy
host-side preprocessing (CHW float arrays)."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "ToTensor", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "Transpose", "Pad",
           "BaseTransform", "BrightnessTransform", "ContrastTransform",
           "SaturationTransform", "HueTransform", "ColorJitter",
           "Grayscale", "RandomVerticalFlip", "RandomRotation",
           "RandomResizedCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return ((np.asarray(img, np.float32) - self.mean) / self.std)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 3 and arr.shape[-1] in (1, 3) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        target = ((arr.shape[0],) + self.size) if chw else (self.size + arr.shape[2:])
        out = jax.image.resize(jnp.asarray(arr), target, method="bilinear")
        return np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[..., i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)]
            arr = np.pad(arr, pads)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        pads = [(0, 0)] * (arr.ndim - 2) + [(p[1], p[3]), (p[0], p[2])]
        return np.pad(arr, pads, constant_values=self.fill)


class BaseTransform:
    """Base class with the reference's keys/params contract
    (reference: transforms.py BaseTransform) — subclasses implement
    `_apply_image`."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, img):
        return self._apply_image(img)


def _hwc(arr):
    """Return (img_hwc float32, was_chw) for a CHW or HWC array."""
    arr = np.asarray(arr, np.float32)
    if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3):
        return arr.transpose(1, 2, 0), True
    return arr, False


def _restore(img, was_chw):
    return img.transpose(2, 0, 1) if was_chw else img


class BrightnessTransform(BaseTransform):
    """reference: transforms.py BrightnessTransform — scale by a random
    factor in [max(0, 1-value), 1+value]."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("brightness value should be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img, np.float32)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return np.asarray(img, np.float32) * f


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img, np.float32)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        arr = np.asarray(img, np.float32)
        mean = arr.mean()
        return (arr - mean) * f + mean


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("saturation value should be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img, np.float32)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        arr, chw = _hwc(img)
        gray = arr @ np.array([0.299, 0.587, 0.114], np.float32) \
            if arr.ndim == 3 and arr.shape[-1] == 3 else arr
        gray = gray[..., None] if gray.ndim == 2 else gray
        return _restore(arr * f + gray * (1 - f), chw)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img, np.float32)
        arr, chw = _hwc(img)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            return _restore(arr, chw)
        shift = np.random.uniform(-self.value, self.value)
        scale = 255.0 if arr.max() > 1.5 else 1.0
        x = arr / scale
        # RGB -> HSV hue rotation -> RGB (vectorized)
        mx = x.max(-1)
        mn = x.min(-1)
        diff = mx - mn + 1e-12
        r, g, b = x[..., 0], x[..., 1], x[..., 2]
        h = np.where(mx == r, (g - b) / diff % 6,
                     np.where(mx == g, (b - r) / diff + 2,
                              (r - g) / diff + 4)) / 6.0
        h = (h + shift) % 1.0
        s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
        v = mx
        i = np.floor(h * 6).astype(np.int32) % 6
        f = h * 6 - np.floor(h * 6)
        p = v * (1 - s)
        q = v * (1 - f * s)
        t = v * (1 - (1 - f) * s)
        out = np.zeros_like(x)
        for idx, (rr, gg, bb) in enumerate(
                [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v),
                 (v, p, q)]):
            m = i == idx
            out[..., 0] = np.where(m, rr, out[..., 0])
            out[..., 1] = np.where(m, gg, out[..., 1])
            out[..., 2] = np.where(m, bb, out[..., 2])
        return _restore(out * scale, chw)


class ColorJitter(BaseTransform):
    """reference: transforms.py ColorJitter — random order of the four
    component transforms."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.parts = [BrightnessTransform(brightness),
                      ContrastTransform(contrast),
                      SaturationTransform(saturation),
                      HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.parts))
        for i in order:
            img = self.parts[i](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr, chw = _hwc(img)
        if arr.ndim == 3 and arr.shape[-1] == 3:
            gray = arr @ np.array([0.299, 0.587, 0.114], np.float32)
        else:
            gray = arr[..., 0] if arr.ndim == 3 else arr
        out = np.repeat(gray[..., None], self.num_output_channels, axis=-1)
        return _restore(out, chw)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr, chw = _hwc(img)
        if np.random.random() < self.prob:
            arr = arr[::-1].copy()
        return _restore(arr, chw)


class RandomRotation(BaseTransform):
    """Rotation by a random angle in `degrees` (reference: transforms.py
    RandomRotation). Nearest-neighbor sampling (the only interpolation
    implemented; other modes raise); honors expand and center."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if interpolation not in ("nearest",):
            raise NotImplementedError(
                f"interpolation {interpolation!r}: only 'nearest' is "
                "implemented")
        if isinstance(degrees, (int, float)):
            if degrees < 0:
                raise ValueError("degrees should be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        arr, chw = _hwc(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        H, W = arr.shape[:2]
        ca, sa = np.cos(angle), np.sin(angle)
        if self.expand:
            # canvas large enough for the whole rotated image
            OH = int(np.ceil(abs(H * ca) + abs(W * sa) - 1e-9))
            OW = int(np.ceil(abs(W * ca) + abs(H * sa) - 1e-9))
        else:
            OH, OW = H, W
        if self.center is not None:
            cx_src, cy_src = float(self.center[0]), float(self.center[1])
        else:
            cy_src, cx_src = (H - 1) / 2.0, (W - 1) / 2.0
        cy_dst, cx_dst = (OH - 1) / 2.0, (OW - 1) / 2.0
        if not self.expand:
            cy_dst, cx_dst = cy_src, cx_src
        yy, xx = np.meshgrid(np.arange(OH), np.arange(OW), indexing="ij")
        src_y = ca * (yy - cy_dst) + sa * (xx - cx_dst) + cy_src
        src_x = -sa * (yy - cy_dst) + ca * (xx - cx_dst) + cx_src
        sy = np.round(src_y).astype(np.int64)
        sx = np.round(src_x).astype(np.int64)
        valid = (sy >= 0) & (sy < H) & (sx >= 0) & (sx < W)
        out_shape = (OH, OW) + arr.shape[2:]
        out = np.full(out_shape, self.fill, dtype=np.float32)
        out[valid] = arr[sy[valid], sx[valid]]
        return _restore(out, chw)


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop then resize (reference: transforms.py
    RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr, chw = _hwc(img)
        H, W = arr.shape[:2]
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                crop = arr[i:i + h, j:j + w]
                break
        else:
            s = min(H, W)
            i, j = (H - s) // 2, (W - s) // 2
            crop = arr[i:i + s, j:j + s]
        return self._resize(_restore(crop, chw))
