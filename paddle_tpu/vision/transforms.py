"""Vision transforms (reference: python/paddle/vision/transforms/) — numpy
host-side preprocessing (CHW float arrays)."""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "Resize", "ToTensor", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "Transpose", "Pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return ((np.asarray(img, np.float32) - self.mean) / self.std)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 3 and arr.shape[-1] in (1, 3) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        target = ((arr.shape[0],) + self.size) if chw else (self.size + arr.shape[2:])
        out = jax.image.resize(jnp.asarray(arr), target, method="bilinear")
        return np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[..., i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)]
            arr = np.pad(arr, pads)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        pads = [(0, 0)] * (arr.ndim - 2) + [(p[1], p[3]), (p[0], p[2])]
        return np.pad(arr, pads, constant_values=self.fill)
