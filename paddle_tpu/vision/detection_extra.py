"""Detection-op tail: matching, FPN routing, box utilities, plus the
ranking/recsys losses that ride the same SSD/CTR pipelines.

reference parity: fluid/layers/detection.py — bipartite_match(:1324,
greedy max-distance column->row matching, operators/detection/
bipartite_match_op.cc), box_clip(:3050), density_prior_box(:1932),
distribute_fpn_proposals(:3680), collect_fpn_proposals(:3878);
fluid/layers/loss.py — bpr_loss(:156), center_loss(:57);
fluid/layers/nn.py — add_position_encoding(:13231);
operators/cvm_op.cc (continuous-value model feature op).

TPU-native notes: bipartite matching is a sequential greedy argmax — a
`lax.scan` over columns with row masking (static shapes, jittable);
FPN distribute keeps static shapes by returning per-level MASKS +
reordered indices instead of ragged splits (callers gather with the
mask counts); the rest are elementwise/index math.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply

__all__ = ["bipartite_match", "box_clip", "density_prior_box",
           "distribute_fpn_proposals", "collect_fpn_proposals",
           "bpr_loss", "center_loss", "cvm", "add_position_encoding",
           "crf_decoding"]


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy max-distance bipartite matching (reference:
    detection.py:1324 / bipartite_match_op.cc BipartiteMatch).

    dist_matrix: [R, C] (rows = candidates, cols = targets... reference
    matches each COLUMN to a row). Returns (match_indices [1, C] int32
    with -1 for unmatched, match_distance [1, C] f32). match_type
    'per_prediction' additionally matches unassigned columns to their
    argmax row when distance > dist_threshold.
    """

    def _match(d):
        R, C = d.shape
        NEG = jnp.asarray(-1e30, d.dtype)

        def step(carry, _):
            dm, col_idx, col_dist = carry
            # best remaining (row, col) pair; row/col exclusion is the
            # NEG fill of the chosen row and column
            flat = jnp.argmax(dm)
            r, c = flat // C, flat % C
            best = dm[r, c]
            valid = best > NEG / 2
            col_idx = jnp.where(valid, col_idx.at[c].set(r.astype(jnp.int32)),
                                col_idx)
            col_dist = jnp.where(valid, col_dist.at[c].set(best), col_dist)
            dm = jnp.where(valid, dm.at[r, :].set(NEG).at[:, c].set(NEG), dm)
            return (dm, col_idx, col_dist), None

        n = min(R, C)
        init = (d.astype(jnp.float32),
                jnp.full((C,), -1, jnp.int32),
                jnp.zeros((C,), jnp.float32))
        (dm, col_idx, col_dist), _ = lax.scan(step, init, None, length=n)

        if match_type == "per_prediction":
            thr = 0.5 if dist_threshold is None else float(dist_threshold)
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_val = jnp.max(d, axis=0).astype(jnp.float32)
            take = (col_idx < 0) & (best_val > thr)
            col_idx = jnp.where(take, best_row, col_idx)
            col_dist = jnp.where(take, best_val, col_dist)
        return col_idx[None, :], col_dist[None, :]

    return apply(_match, dist_matrix, name="bipartite_match")


def box_clip(input, im_info, name=None):
    """Clip boxes to image boundaries (reference: detection.py:3050;
    im_info rows are [height, width, scale])."""

    def _clip(boxes, info):
        h = info[..., 0] / info[..., 2]
        w = info[..., 1] / info[..., 2]
        hm = (h - 1.0).reshape((-1,) + (1,) * (boxes.ndim - 2))
        wm = (w - 1.0).reshape((-1,) + (1,) * (boxes.ndim - 2))
        x1 = jnp.clip(boxes[..., 0], 0.0, None)
        y1 = jnp.clip(boxes[..., 1], 0.0, None)
        x2 = boxes[..., 2]
        y2 = boxes[..., 3]
        if boxes.ndim >= 2:
            x1 = jnp.minimum(x1, wm)
            y1 = jnp.minimum(y1, hm)
            x2 = jnp.clip(jnp.minimum(x2, wm), 0.0, None)
            y2 = jnp.clip(jnp.minimum(y2, hm), 0.0, None)
        return jnp.stack([x1, y1, x2, y2], axis=-1)

    return apply(_clip, input, im_info, name="box_clip")


def density_prior_box(input, image=None, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """Density prior boxes (reference: detection.py:1932 /
    density_prior_box_op.h): per feature-map cell, boxes of the fixed
    sizes/ratios on density x density sub-grids spaced by
    step_average/density (step_average = int((step_w + step_h)/2), the
    reference's spacing — NOT the box size).

    Pure index math over static shapes: computed host-side with
    vectorized numpy (like prior_box), no device op involved."""
    import numpy as np

    densities = list(densities or [])
    fixed_sizes = list(fixed_sizes or [])
    fixed_ratios = list(fixed_ratios or [])

    feat = input._data if isinstance(input, Tensor) else input
    img = (image._data if isinstance(image, Tensor) else image) \
        if image is not None else feat
    H, W = int(feat.shape[-2]), int(feat.shape[-1])
    img_h, img_w = int(img.shape[-2]), int(img.shape[-1])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H
    step_avg = int((step_w + step_h) * 0.5)

    cx = (np.arange(W) + offset) * step_w                 # [W]
    cy = (np.arange(H) + offset) * step_h                 # [H]
    per_cell = []
    for size, dens in zip(fixed_sizes, densities):
        shift = step_avg / dens
        sub = -step_avg / 2.0 + shift / 2.0 + np.arange(dens) * shift
        for ratio in fixed_ratios:
            bw = size * math.sqrt(ratio) / 2.0
            bh = size / math.sqrt(ratio) / 2.0
            dxx, dyy = np.meshgrid(sub, sub)              # [dens, dens]
            per_cell.append(np.stack(
                [dxx - bw, dyy - bh, dxx + bw, dyy + bh],
                axis=-1).reshape(-1, 4))
    offsets = np.concatenate(per_cell, axis=0)            # [K, 4]
    cxy = np.stack(np.meshgrid(cx, cy), axis=-1)          # [H, W, 2] (x, y)
    centers = np.concatenate([cxy, cxy], axis=-1)         # [H, W, 4]
    out = centers[:, :, None, :] + offsets[None, None]    # [H, W, K, 4]
    out = out / np.array([img_w, img_h, img_w, img_h], np.float32)
    out = out.astype(np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).astype(np.float32)
    if flatten_to_2d:
        out, var = out.reshape(-1, 4), var.reshape(-1, 4).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (reference: detection.py:3680).

    TPU-native static shapes: returns (multi_rois, restore_ind,
    level_counts) where `multi_rois` is a list with ONE [N, 4] tensor per
    level holding that level's rois FIRST (padded with zeros after
    `level_counts[i]` rows) — callers slice with the counts; restore_ind
    [N, 1] maps the concatenated per-level order back to the input order.
    """
    nlevels = max_level - min_level + 1

    def _dist(rois):
        area = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0) * \
            jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
        scale = jnp.sqrt(area)
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs = []
        N = rois.shape[0]
        order = jnp.argsort(lvl, stable=True)
        counts = []
        for i in range(nlevels):
            mask = lvl == (min_level + i)
            cnt = jnp.sum(mask.astype(jnp.int32))
            # stable-sort rois of this level to the front
            key = jnp.where(mask, 0, 1)
            idx = jnp.argsort(key, stable=True)
            outs.append(jnp.where((jnp.arange(N) < cnt)[:, None],
                                  rois[idx], 0.0))
            counts.append(cnt)
        restore = jnp.argsort(order, stable=True).astype(jnp.int32)[:, None]
        return tuple(outs) + (restore, jnp.stack(counts))

    res = apply(_dist, fpn_rois, name="distribute_fpn_proposals")
    multi_rois = list(res[:nlevels])
    restore_ind, counts = res[nlevels], res[nlevels + 1]
    # counts (rois per level) are ALWAYS returned — the static-shape
    # padding makes them load-bearing, unlike the reference where the
    # ragged splits carry their own lengths
    return multi_rois, restore_ind, counts


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-level proposals and keep the top-scoring
    `post_nms_top_n` (reference: detection.py:3878). Static shapes: with
    `rois_num_per_level` (the counts from distribute_fpn_proposals),
    pad rows beyond each level's count are masked to -inf so they can
    never outrank real proposals."""
    k = len(multi_rois)

    def _collect(*arrs):
        rois_l = arrs[:k]
        scores_l = [a.reshape(-1) for a in arrs[k:2 * k]]
        if rois_num_per_level is not None:
            counts = arrs[2 * k]
            scores_l = [jnp.where(jnp.arange(s.shape[0]) < counts[i],
                                  s, -jnp.inf)
                        for i, s in enumerate(scores_l)]
        rois = jnp.concatenate(rois_l, axis=0)
        scores = jnp.concatenate(scores_l, axis=0)
        n = min(int(post_nms_top_n), scores.shape[0])
        top_s, top_i = lax.top_k(scores, n)
        return rois[top_i], top_s[:, None]

    args = list(multi_rois) + list(multi_scores)
    if rois_num_per_level is not None:
        args.append(rois_num_per_level)
    return apply(_collect, *args, name="collect_fpn_proposals")


def bpr_loss(input, label, name=None):
    """Bayesian Personalized Ranking loss (reference: loss.py:156 /
    bpr_loss_op.cc): -mean over j != label of log sigmoid(x_label - x_j).
    """

    def _bpr(x, y):
        B, C = x.shape
        ids = y.astype(jnp.int32).reshape(-1)
        pos = jnp.take_along_axis(x, ids[:, None], axis=1)
        diff = pos - x
        logsig = jax.nn.log_sigmoid(diff)
        mask = jax.nn.one_hot(ids, C, dtype=x.dtype)
        per = -jnp.sum(logsig * (1.0 - mask), axis=1) / max(C - 1, 1)
        return per[:, None]

    return apply(_bpr, input, label, name="bpr_loss")


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True, centers=None, name=None):
    """Center loss (reference: loss.py:57 / center_loss_op.cc): pulls
    features toward their class centers; centers update by EMA when
    `update_center` (eager mode).

    Returns (loss [N, 1], centers). Pass the returned centers back in to
    keep state across steps (functional-state form of the reference's
    persistable center table)."""
    if centers is None:
        dim = int(input.shape[-1])
        centers = Tensor(jnp.zeros((num_classes, dim), jnp.float32))

    def _cl(x, y, c):
        ids = y.astype(jnp.int32).reshape(-1)
        cx = jnp.take(c, ids, axis=0)
        diff = x - cx
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        return loss, diff

    loss, diff = apply(_cl, input, label, centers, name="center_loss")
    if update_center:
        ids = jnp.asarray(
            (label._data if isinstance(label, Tensor) else label)
        ).astype(jnp.int32).reshape(-1)
        counts = jnp.zeros((centers.shape[0],), jnp.float32) \
            .at[ids].add(1.0)
        upd = jnp.zeros_like(centers._data).at[ids].add(
            jnp.asarray(diff._data))
        denom = (counts + 1.0)[:, None]
        centers._data = centers._data + alpha * upd / denom
    return loss, centers


def cvm(input, cvm_input, use_cvm=True, name=None):
    """Continuous-value model op (reference: cvm_op.cc): the first two
    lanes are show/click; use_cvm=True keeps them log-adjusted, False
    strips them."""

    def _cvm(x, sc):
        show = jnp.log(sc[:, :1] + 1.0)
        click = jnp.log(sc[:, 1:2] + 1.0) - jnp.log(sc[:, :1] + 1.0)
        if use_cvm:
            return jnp.concatenate([show, click, x[:, 2:]], axis=1)
        return x[:, 2:]

    return apply(_cvm, input, cvm_input, name="cvm")


def add_position_encoding(input, alpha, beta, name=None):
    """Sinusoidal position encoding mix (reference: nn.py:13231 /
    add_position_encoding_op.cc): out = alpha*x + beta*PE."""

    def _ape(x):
        B, S, E = x.shape
        half = E // 2
        pos = jnp.arange(S, dtype=jnp.float32)[:, None]
        div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
        ang = pos / div[None, :]
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)
        if pe.shape[1] < E:
            pe = jnp.pad(pe, ((0, 0), (0, E - pe.shape[1])))
        return alpha * x + beta * pe[None, :, :]

    return apply(_ape, input, name="add_position_encoding")


def crf_decoding(input, transition, label=None, length=None, name=None):
    """Viterbi decode alias in the CRF naming (reference:
    crf_decoding_op.cc): returns the best path [B, S] (and, with label,
    a 0/1 correctness mask like the reference's evaluation mode)."""
    from ..text.viterbi import viterbi_decode

    B, S = int(input.shape[0]), int(input.shape[1])
    if length is None:
        length = Tensor(jnp.full((B,), S, jnp.int32))
    scores, path = viterbi_decode(input, transition, length,
                                  include_bos_eos_tag=False)
    if label is not None:
        def _cmp(p, lab):
            return (p == lab.astype(p.dtype)).astype(jnp.int64)
        return apply(_cmp, path, label, name="crf_decoding_eval")
    return path
