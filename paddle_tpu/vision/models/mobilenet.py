"""MobileNet V1/V2 (reference: python/paddle/vision/models/mobilenetv1.py, v2).

``data_format="NHWC"`` runs the feature extractor channels-last internally
via the nn.layout planner (one transpose at entry, one at exit — the TPU
MXU-native conv layout) while the public NCHW contract is unchanged; the
conv→BN→ReLU6 triples run as single fused ops (nn.fused_conv_bn_act).
"""

from __future__ import annotations

from ... import nn
from ...nn import layout as _layout

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1):
        pad = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6(),
        )

    def forward(self, x):
        conv, bn, _ = self._sub_layers.values()
        return nn.fused_conv_bn_act(conv, bn, x, "relu6")


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = _ConvBNReLU(in_c, in_c, 3, stride, groups=in_c)
        self.pw = _ConvBNReLU(in_c, out_c, 1, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = _layout.check_data_format(data_format)
        s = lambda c: max(int(c * scale), 8)
        cfg = [(s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
               (s(128), s(256), 2), (s(256), s(256), 1), (s(256), s(512), 2)] + \
              [(s(512), s(512), 1)] * 5 + \
              [(s(512), s(1024), 2), (s(1024), s(1024), 1)]
        layers = [_ConvBNReLU(3, s(32), 3, 2)]
        for in_c, out_c, stride in cfg:
            layers.append(_DepthwiseSeparable(in_c, out_c, stride))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        # NHWC flag: the planner keeps the whole conv stack channels-last;
        # the pool consumes the tag and flatten restores NCHW order, so the
        # head sees identical features either way
        with _layout.channels_last_scope(self.data_format == "NHWC"):
            x = self.features(x)
            if self.with_pool:
                x = self.pool(x)
            if self.num_classes > 0:
                from ...tensor.manipulation import flatten
                x = self.fc(flatten(x, 1))
            x = _layout.ensure_channels_first(x)
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride, groups=hidden),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = _layout.check_data_format(data_format)
        cfg = [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = max(int(32 * scale), 8)
        last_c = max(int(1280 * scale), 1280)
        layers = [_ConvBNReLU(3, in_c, 3, 2)]
        for t, c, n, s in cfg:
            out_c = max(int(c * scale), 8)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last_c, 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        with _layout.channels_last_scope(self.data_format == "NHWC"):
            x = self.features(x)
            if self.with_pool:
                x = self.pool(x)
            if self.num_classes > 0:
                from ...tensor.manipulation import flatten
                x = self.classifier(flatten(x, 1))
            x = _layout.ensure_channels_first(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
