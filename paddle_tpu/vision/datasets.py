"""Vision datasets (reference: python/paddle/vision/datasets/).

The build environment has zero egress, so `download=True` raises and every
dataset supports a deterministic synthetic mode (used by tests/benchmarks)
or loading from pre-downloaded files on disk.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers", "DatasetFolder"]


class _SyntheticClassification(Dataset):
    """Deterministic synthetic images: class-dependent patterns + noise, so
    small models genuinely learn (loss decreases) without real data."""

    def __init__(self, num_samples, image_shape, num_classes, seed=0,
                 transform=None):
        self.num_samples = num_samples
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        # one fixed template per class
        self.templates = rng.uniform(0.0, 1.0,
                                     (num_classes,) + image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes, num_samples).astype(np.int64)
        self.noise_seeds = rng.randint(0, 2 ** 31 - 1, num_samples)

    def __getitem__(self, idx):
        label = self.labels[idx]
        rng = np.random.RandomState(self.noise_seeds[idx])
        img = self.templates[label] + 0.3 * rng.randn(*self.image_shape).astype(np.float32)
        img = img.astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py.

    Loads idx-format files when `image_path`/`label_path` exist; otherwise
    falls back to the synthetic generator (no-egress environment)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 num_synthetic=2048):
        self.mode = mode
        self.transform = transform
        if image_path and label_path and os.path.exists(image_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
        else:
            n = num_synthetic if mode == "train" else max(num_synthetic // 4, 256)
            syn = _SyntheticClassification(n, (1, 28, 28), 10,
                                           seed=0 if mode == "train" else 1)
            self._syn = syn
            self.images = None
            self.labels = syn.labels

    @staticmethod
    def _load_idx(image_path, label_path):
        with gzip.open(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, 1, rows, cols)
        with gzip.open(label_path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        return images.astype(np.float32) / 255.0, labels

    def __getitem__(self, idx):
        if self.images is None:
            img, label = self._syn[idx]
        else:
            img, label = self.images[idx], self.labels[idx]
            if self.transform is not None:
                img = self.transform(img)
        return img, np.asarray([label], np.int64)

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, num_synthetic=2048):
        self.transform = transform
        n = num_synthetic if mode == "train" else max(num_synthetic // 4, 256)
        self._syn = _SyntheticClassification(n, (3, 32, 32), 10,
                                             seed=2 if mode == "train" else 3,
                                             transform=transform)

    def __getitem__(self, idx):
        img, label = self._syn[idx]
        return img, np.int64(label)

    def __len__(self):
        return len(self._syn)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, num_synthetic=2048):
        self.transform = transform
        n = num_synthetic if mode == "train" else max(num_synthetic // 4, 256)
        self._syn = _SyntheticClassification(n, (3, 32, 32), 100,
                                             seed=4 if mode == "train" else 5,
                                             transform=transform)


class Flowers(Cifar10):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None,
                 num_synthetic=1024):
        self.transform = transform
        n = num_synthetic
        self._syn = _SyntheticClassification(n, (3, 64, 64), 102, seed=6,
                                             transform=transform)


class DatasetFolder(Dataset):
    """ImageFolder-style dataset over a directory tree of class subdirs."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or (".npy",)
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        return np.load(path)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.samples)
