"""Vision/detection operators.

reference parity: python/paddle/vision/ops.py — yolo_box(:252),
deform_conv2d(:423), read_file(:819), decode_jpeg(:864),
psroi_pool(:911), roi_pool(:1022), roi_align(:1145), nms (2.x surface;
CUDA kernels under operators/detection/). decode_jpeg decodes host-side
via PIL (the nvjpeg analogue on TPU systems is host IO).

TPU-native notes: NMS is sequential by nature — implemented as a
fixed-iteration `lax.while_loop`-free greedy scan with static shapes
(compiles under jit; returns a padded index tensor + count). roi_align is
a fully vectorized bilinear gather (static sampling grid), the classic
TPU-friendly formulation of the CUDA kernel's per-bin loop.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import matmul_precision
from ..core.tensor import Tensor, apply

__all__ = ["box_iou", "nms", "roi_align", "roi_pool", "yolo_box",
           "psroi_pool", "deform_conv2d", "read_file", "decode_jpeg"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _roi_batch_index(boxes_num, n_rois):
    """boxes_num [N] -> per-roi batch index [n_rois] (shared by the RoI
    pool family)."""
    bn = jnp.asarray(boxes_num._data if isinstance(boxes_num, Tensor)
                     else boxes_num)
    return jnp.repeat(jnp.arange(bn.shape[0]), bn,
                      total_repeat_length=n_rois)


def _bin_sample_grid(start, bin_size, n_bins, sr, center=True):
    """Per-roi sampling coordinates [R, n_bins, sr] along one axis:
    start + (bin + (s [+0.5])/sr) * bin_size."""
    offs = (jnp.arange(sr) + 0.5) / sr if center else jnp.arange(sr) / sr
    grid = jnp.arange(n_bins)[None, :, None] + offs[None, None, :]
    return start[:, None, None] + grid * bin_size[:, None, None]


def _iou_arrays(a, b):
    """Raw-array pairwise IoU (shared by box_iou and nms)."""
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-10)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of [N, 4] and [M, 4] xyxy boxes -> [N, M]."""
    return apply(_iou_arrays, _t(boxes1), _t(boxes2), name="box_iou")


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None,
        name=None):
    """Greedy non-maximum suppression (reference: vision/ops.py nms /
    operators/detection/nms_op). Returns kept indices sorted by score.

    Static-shape jit-friendly core: N iterations of suppress-the-rest;
    category-aware when category_idxs is given (boxes only suppress within
    their own category, the reference's batched path).
    """
    b = _t(boxes)
    n = b.shape[0]
    s = _t(scores) if scores is not None else None

    def _nms(bx, *maybe_s):
        order = (jnp.argsort(-maybe_s[0]) if maybe_s
                 else jnp.arange(bx.shape[0]))
        bx_sorted = bx[order]
        iou = _iou_arrays(bx_sorted, bx_sorted)
        if category_idxs is not None:
            cats = jnp.asarray(
                category_idxs._data if isinstance(category_idxs, Tensor)
                else category_idxs)[order]
            same = cats[:, None] == cats[None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            # suppress j>i overlapping a KEPT i
            sup = (iou[i] > iou_threshold) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        kept_sorted = jnp.nonzero(keep, size=n, fill_value=-1)[0]
        idx = jnp.where(kept_sorted >= 0, order[kept_sorted], -1)
        return idx, jnp.sum(keep)

    args = [b] + ([s] if s is not None else [])
    idx, count = apply(_nms, *args, name="nms")
    # eager convenience: trim padding when not tracing
    try:
        c = int(np.asarray(count.data))
        idx = idx[:c]
    except Exception:
        pass
    if top_k is not None:
        idx = idx[:top_k]
    return idx


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """RoIAlign (reference: vision/ops.py:1145, roi_align_op.cu): bilinear
    sampling on a static grid per output bin, averaged."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def _ra(feat, rois):
        N, C, H, W = feat.shape
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ys = _bin_sample_grid(y1, bin_h, ph, sr)               # [R, ph, sr]
        xs = _bin_sample_grid(x1, bin_w, pw, sr)               # [R, pw, sr]
        batch_idx = _roi_batch_index(boxes_num, rois.shape[0])

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [ph, sr]; xx [pw, sr]
            y = jnp.clip(yy, 0, H - 1)
            xc = jnp.clip(xx, 0, W - 1)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(xc).astype(jnp.int32)
            y1i = jnp.minimum(y0 + 1, H - 1)
            x1i = jnp.minimum(x0 + 1, W - 1)
            wy = y - y0
            wx = xc - x0
            # gather corners: [C, ph, sr, pw, sr]
            g = lambda yi, xi: img[:, yi[:, :, None, None],  # noqa: E731
                                   xi[None, None, :, :]]
            val = (g(y0, x0) * ((1 - wy)[:, :, None, None]
                                * (1 - wx)[None, None])
                   + g(y0, x1i) * ((1 - wy)[:, :, None, None]
                                   * wx[None, None])
                   + g(y1i, x0) * (wy[:, :, None, None]
                                   * (1 - wx)[None, None])
                   + g(y1i, x1i) * (wy[:, :, None, None] * wx[None, None]))
            return val.mean(axis=(2, 4))        # avg over samples

        out = jax.vmap(lambda bi, yy, xx: bilinear(feat[bi], yy, xx))(
            batch_idx, ys, xs)
        return out                               # [R, C, ph, pw]

    return apply(_ra, _t(x), _t(boxes), name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None):
    """RoIPool: exact max over every integer position in each adaptive bin
    (reference: vision/ops.py:1022, operators/roi_pool_op). Bin boundaries
    use the reference math exactly — rounded UNCLIPPED RoI coords give
    rw/rh, each bin is then clipped to the image, fully-clipped bins
    return 0.

    Static-shape TPU formulation: a bin may span anywhere from 0 to the
    whole image, so instead of bounding positions-per-bin each axis is
    reduced with a sparse-table range max: sliding power-of-2 window maxima
    are built level by level (log2(size) levels), and every bin's
    [start, end) max is two gathers from the level matching its width. The
    levels are swept progressively — one live window buffer, never a
    stacked [L, ...] table and never a per-RoI copy — so peak memory is
    one [R, pw, C, H] intermediate. Exact for every bin size.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def _round_c(v):
        # C round(): half away from zero (jnp.round is half-to-even)
        return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

    def _rp(feat, rois):
        N, C, H, W = feat.shape
        x1 = _round_c(rois[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = _round_c(rois[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = _round_c(rois[:, 2] * spatial_scale).astype(jnp.int32)
        y2 = _round_c(rois[:, 3] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)

        def bin_edges(start, rsz, nb, size):
            # [R, nb] int bin [start, end) per reference: floor/ceil of the
            # adaptive boundary offset by the RoI start, clipped to image.
            # Pure integer arithmetic — float division would overshoot
            # exact boundaries (e.g. 21/7 -> 3.0000002 under XLA).
            i = jnp.arange(nb, dtype=jnp.int32)
            bs = (i[None, :] * rsz[:, None]) // nb + start[:, None]
            be = -((-(i[None, :] + 1) * rsz[:, None]) // nb) + start[:, None]
            return (jnp.clip(bs, 0, size).astype(jnp.int32),
                    jnp.clip(be, 0, size).astype(jnp.int32))

        hs, he = bin_edges(y1, rh, ph, H)         # [R, ph]
        ws, we = bin_edges(x1, rw, pw, W)         # [R, pw]
        R = rois.shape[0]
        batch_idx = _roi_batch_index(boxes_num, R)
        import math as _m

        def _qlevel(s, e, size):
            # sparse-table level per [s, e) query: lvl = floor(log2(e-s)),
            # computed with integer comparisons (no float-log edge cases)
            ln = jnp.maximum(e - s, 1)
            lvl = jnp.zeros(ln.shape, jnp.int32)
            k = 1
            while (1 << k) <= size:
                lvl = lvl + (ln >= (1 << k)).astype(jnp.int32)
                k += 1
            return lvl, jnp.left_shift(jnp.int32(1), lvl)

        def _shift_max(cur, p):
            # cur[..., s] = max over a window of p: widen to 2p
            pad = jnp.full(cur.shape[:-1] + (p,), -jnp.inf, cur.dtype)
            return jnp.maximum(
                cur, jnp.concatenate([cur[..., p:], pad], axis=-1))

        # stage 1 — column range max: colmax[r, j, c, h] =
        # max(feat[bi_r, c, h, ws_rj:we_rj])
        lvl_x, pow_x = _qlevel(ws, we, W)            # [R, pw]
        sx = jnp.clip(ws, 0, W - 1)
        ex = jnp.clip(we - pow_x, 0, W - 1)
        colmax = jnp.full((R, pw, feat.shape[1], H), -jnp.inf, feat.dtype)
        cur = feat                                # [N, C, H, W]
        for lv in range(max(1, int(_m.floor(_m.log2(W))) + 1)):
            v = jnp.maximum(cur[batch_idx[:, None], :, :, sx],
                            cur[batch_idx[:, None], :, :, ex])
            colmax = jnp.where((lvl_x == lv)[:, :, None, None], v, colmax)
            cur = _shift_max(cur, 1 << lv)

        # stage 2 — row range max over colmax's h axis
        lvl_y, pow_y = _qlevel(hs, he, H)            # [R, ph]
        sy = jnp.clip(hs, 0, H - 1)
        ey = jnp.clip(he - pow_y, 0, H - 1)
        ridx = jnp.arange(R)[:, None]
        out = jnp.full((R, ph, pw, feat.shape[1]), -jnp.inf, feat.dtype)
        cur = colmax                              # [R, pw, C, H]
        for lv in range(max(1, int(_m.floor(_m.log2(H))) + 1)):
            v = jnp.maximum(cur[ridx, :, :, sy],  # [R, ph, pw, C]
                            cur[ridx, :, :, ey])
            out = jnp.where((lvl_y == lv)[:, :, None, None], v, out)
            cur = _shift_max(cur, 1 << lv)

        out = jnp.transpose(out, (0, 3, 1, 2))    # [R, C, ph, pw]
        empty = (he <= hs)[:, :, None] | (we <= ws)[:, None, :]
        return jnp.where(empty[:, None], 0.0, out)

    return apply(_rp, _t(x), _t(boxes), name="roi_pool")


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox: bool = True, name=None,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5):
    """Decode YOLOv3 head output into boxes + scores (reference:
    vision/ops.py:252, yolo_box_op). x: [N, A*(5+cls), H, W]."""
    if iou_aware:
        raise NotImplementedError(
            "yolo_box: iou_aware heads (extra A iou channels, conf = "
            "conf^(1-f) * iou^f) are not implemented")
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def _yb(xa, imgs):
        N, _, H, W = xa.shape
        pred = xa.reshape(N, A, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (sig(pred[:, :, 0]) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gx) / W
        by = (sig(pred[:, :, 1]) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gy) / H
        aw = jnp.asarray(anchors[:, 0])[None, :, None, None]
        ah = jnp.asarray(anchors[:, 1])[None, :, None, None]
        input_w = W * downsample_ratio
        input_h = H * downsample_ratio
        bw = jnp.exp(pred[:, :, 2]) * aw / input_w
        bh = jnp.exp(pred[:, :, 3]) * ah / input_h
        conf = sig(pred[:, :, 4])
        probs = sig(pred[:, :, 5:]) * conf[:, :, None]
        im_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        im_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * im_w
        y1 = (by - bh / 2) * im_h
        x2 = (bx + bw / 2) * im_w
        y2 = (by + bh / 2) * im_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, im_w - 1)
            y1 = jnp.clip(y1, 0, im_h - 1)
            x2 = jnp.clip(x2, 0, im_w - 1)
            y2 = jnp.clip(y2, 0, im_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) \
            .reshape(N, A * H * W, 4)
        scores = probs.transpose(0, 1, 3, 4, 2) \
            .reshape(N, A * H * W, class_num)
        mask = (conf.reshape(N, A * H * W) >= conf_thresh)[..., None]
        return boxes * mask, scores * mask

    return apply(_yb, _t(x), _t(img_size), name="yolo_box")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
               name=None):
    """Position-sensitive RoI pooling (reference: vision/ops.py:911,
    psroi_pool_op): input channels C = out_c * ph * pw; bin (i, j) of
    output channel k averages input channel k*ph*pw + i*pw + j over the
    bin's area."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def _ps(feat, rois):
        N, C, H, W = feat.shape
        if C % (ph * pw):
            raise ValueError(
                f"psroi_pool needs output_size {ph}x{pw} to divide the "
                f"channel count, got C={C}")
        out_c = C // (ph * pw)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        sr = 4
        ys = _bin_sample_grid(y1, rh / ph, ph, sr)
        xs = _bin_sample_grid(x1, rw / pw, pw, sr)
        batch_idx = _roi_batch_index(boxes_num, rois.shape[0])
        # per-bin channel map [out_c, ph, pw]
        chan = (jnp.arange(out_c)[:, None, None] * (ph * pw)
                + jnp.arange(ph)[None, :, None] * pw
                + jnp.arange(pw)[None, None, :])

        def pool(img, yy, xx):
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)   # [ph, sr]
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)   # [pw, sr]
            # [C, ph, sr, pw, sr] -> bin means [C, ph, pw]
            vals = img[:, yi[:, :, None, None], xi[None, None, :, :]] \
                .mean(axis=(2, 4))
            return vals[chan, jnp.arange(ph)[None, :, None],
                        jnp.arange(pw)[None, None, :]]      # [out_c, ph, pw]

        return jax.vmap(lambda bi, yy, xx: pool(feat[bi], yy, xx))(
            batch_idx, ys, xs)

    return apply(_ps, _t(x), _t(boxes), name="psroi_pool")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups: int = 1, groups: int = 1,
                  mask=None, name=None):
    """Deformable convolution v1/v2 (reference: vision/ops.py:423,
    deformable_conv_op.cu): per-output-position learned offsets displace
    each kernel tap; v2 additionally modulates taps with ``mask``.

    TPU formulation: bilinear-gather all K taps into an im2col tensor
    [N, C*K, oH, oW] (one vectorized gather — no per-pixel loops), then
    one grouped 1x1 matmul. Supports deformable_groups/groups.
    """
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = _pair(stride)
    padh, padw = _pair(padding)
    dh, dw = _pair(dilation)

    def _dc(xa, off, w, *rest):
        maybe_mask = rest[0] if (mask is not None) else None
        b = None
        if bias is not None:
            b = rest[-1]
        N, C, H, W = xa.shape
        out_c, c_per_g, kh, kw = w.shape
        K = kh * kw
        oH = (H + 2 * padh - (dh * (kh - 1) + 1)) // sh + 1
        oW = (W + 2 * padw - (dw * (kw - 1) + 1)) // sw + 1
        dg = deformable_groups
        c_per_dg = C // dg

        # base sampling grid per tap: [K, oH, oW]
        base_y = (jnp.arange(oH)[None, :, None] * sh - padh
                  + (jnp.arange(kh)[:, None, None] * dh)
                  .repeat(kw, axis=0))
        base_x = (jnp.arange(oW)[None, None, :] * sw - padw
                  + jnp.tile(jnp.arange(kw), kh)[:, None, None] * dw)
        base_y = jnp.broadcast_to(base_y, (K, oH, oW)).astype(jnp.float32)
        base_x = jnp.broadcast_to(base_x, (K, oH, oW)).astype(jnp.float32)

        # offsets: [N, dg*2*K, oH, oW] -> y/x per (dg, K)
        off = off.reshape(N, dg, 2 * K, oH, oW)
        off_y = off[:, :, 0::2]                     # [N, dg, K, oH, oW]
        off_x = off[:, :, 1::2]
        ys = base_y[None, None] + off_y
        xs = base_x[None, None] + off_x

        def gather_one(img_dg, yy, xx):
            # img_dg [c_per_dg, H, W]; yy/xx [K, oH, oW]
            y = jnp.clip(yy, -1.0, H + 0.0)
            xc = jnp.clip(xx, -1.0, W + 0.0)
            y0 = jnp.floor(y)
            x0 = jnp.floor(xc)
            wy = y - y0
            wx = xc - x0

            def at(yi, xi):
                inb = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
                yi_ = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                xi_ = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                v = img_dg[:, yi_, xi_]             # [c, K, oH, oW]
                return v * inb[None]

            val = (at(y0, x0) * ((1 - wy) * (1 - wx))[None]
                   + at(y0, x0 + 1) * ((1 - wy) * wx)[None]
                   + at(y0 + 1, x0) * (wy * (1 - wx))[None]
                   + at(y0 + 1, x0 + 1) * (wy * wx)[None])
            return val                               # [c, K, oH, oW]

        # vmap over batch and deformable groups
        imgs = xa.reshape(N, dg, c_per_dg, H, W)
        cols = jax.vmap(jax.vmap(gather_one))(imgs, ys, xs)
        # [N, dg, c_per_dg, K, oH, oW] -> [N, C, K, oH, oW]
        cols = cols.reshape(N, C, K, oH, oW)
        if maybe_mask is not None:                   # v2 modulation
            m = maybe_mask.reshape(N, dg, K, oH, oW)
            m = jnp.repeat(m, c_per_dg, axis=1).reshape(N, C, K, oH, oW)
            cols = cols * m

        # grouped contraction with the kernel: w [out_c, c_per_g, kh*kw]
        wg = w.reshape(groups, out_c // groups, c_per_g, K)
        colg = cols.reshape(N, groups, c_per_g, K, oH, oW)
        out = jnp.einsum("ngckhw,gock->ngohw", colg, wg,
                         precision=matmul_precision())
        out = out.reshape(N, out_c, oH, oW)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    args = [_t(x), _t(offset), _t(weight)]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        args.append(_t(bias))
    return apply(_dc, *args, name="deform_conv2d")


def read_file(filename, name=None):
    """Read file bytes as a uint8 tensor (reference: vision/ops.py:819)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode: str = "unchanged", name=None):
    """Decode JPEG bytes to [C, H, W] uint8 (reference: vision/ops.py:864,
    CUDA nvjpeg op). Host-side decode via PIL — image IO is host work on
    TPU systems; the device gets the decoded array."""
    import io

    from PIL import Image

    if mode not in ("unchanged", "gray", "rgb"):
        raise ValueError(f"decode_jpeg mode must be 'unchanged', 'gray' "
                         f"or 'rgb', got {mode!r}")
    raw = np.asarray(x._data if isinstance(x, Tensor) else x,
                     dtype=np.uint8).tobytes()
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


# detection-head op tail (SSD priors, RPN anchors, box codec, NMS post)
from .detection import (anchor_generator, box_coder,  # noqa: E402,F401
                        multiclass_nms, prior_box)

__all__ += ["prior_box", "anchor_generator", "box_coder",
            "multiclass_nms"]

from .detection_extra import (add_position_encoding,  # noqa: E402,F401
                              bipartite_match, box_clip, bpr_loss,
                              center_loss, collect_fpn_proposals,
                              crf_decoding, cvm, density_prior_box,
                              distribute_fpn_proposals)

__all__ += ["bipartite_match", "box_clip", "density_prior_box",
            "distribute_fpn_proposals", "collect_fpn_proposals",
            "bpr_loss", "center_loss", "cvm", "add_position_encoding",
            "crf_decoding"]
