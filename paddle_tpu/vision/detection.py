"""SSD / Faster-RCNN detection-head operators.

reference parity: paddle/fluid/operators/detection/ — prior_box_op.h
(ExpandAspectRatios:29, kernel:53), anchor_generator_op.h(:60),
box_coder_op.h (EncodeCenterSize:41, DecodeCenterSize:118),
multiclass_nms_op.cc (NMSFast:140, attrs:199); python surface
fluid/layers/detection.py prior_box(:1771), anchor_generator,
box_coder, multiclass_nms.

TPU-native notes: prior/anchor generation is pure index math —
vectorized meshgrid broadcasts, no per-pixel loops; box_coder is
elementwise; multiclass_nms is HOST-SIDE post-processing (numpy over
device outputs, like the reference's CPU-only multiclass_nms_op) with
static output shapes ([N, keep_top_k, 6] plus valid counts) — call it
on the readback side of a jitted detection head, not inside jit (the
in-jit building block is ops.nms / vision.ops).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from .ops import _t

__all__ = ["prior_box", "anchor_generator", "box_coder", "multiclass_nms"]


def _expand_aspect_ratios(aspect_ratios, flip: bool) -> List[float]:
    """reference: prior_box_op.h ExpandAspectRatios — 1.0 first, dedup
    (1e-6), optional reciprocal."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - v) < 1e-6 for v in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes over a feature map -> (boxes, variances), each
    [H, W, num_priors, 4] normalized to the image (reference:
    prior_box_op.h kernel; layers/detection.py:1771)."""
    min_sizes = [float(m) for m in (min_sizes if isinstance(
        min_sizes, (list, tuple)) else [min_sizes])]
    max_sizes = [float(m) for m in (max_sizes or [])]
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("max_sizes must pair 1:1 with min_sizes")
    ars = _expand_aspect_ratios(
        aspect_ratios if isinstance(aspect_ratios, (list, tuple))
        else [aspect_ratios], flip)

    in_arr = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    im_arr = image._data if isinstance(image, Tensor) else jnp.asarray(image)
    fh, fw = int(in_arr.shape[2]), int(in_arr.shape[3])
    ih, iw = int(im_arr.shape[2]), int(im_arr.shape[3])
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    # per-position half-sizes in generation order (reference ordering:
    # per min_size -> [ar loop, max] or Caffe [min, max, ars != 1])
    half_sizes = []      # list of (half_w, half_h)
    for s, mn in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            half_sizes.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = math.sqrt(mn * max_sizes[s]) / 2.0
                half_sizes.append((m, m))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                half_sizes.append((mn * math.sqrt(ar) / 2.0,
                                   mn / math.sqrt(ar) / 2.0))
        else:
            for ar in ars:
                half_sizes.append((mn * math.sqrt(ar) / 2.0,
                                   mn / math.sqrt(ar) / 2.0))
            if max_sizes:
                m = math.sqrt(mn * max_sizes[s]) / 2.0
                half_sizes.append((m, m))
    hw = jnp.asarray([p[0] for p in half_sizes], jnp.float32)  # [P]
    hh = jnp.asarray([p[1] for p in half_sizes], jnp.float32)

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w   # [W]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h   # [H]
    x1 = (cx[None, :, None] - hw[None, None, :]) / iw            # [1,W,P]
    y1 = (cy[:, None, None] - hh[None, None, :]) / ih            # [H,1,P]
    x2 = (cx[None, :, None] + hw[None, None, :]) / iw
    y2 = (cy[:, None, None] + hh[None, None, :]) / ih
    boxes = jnp.stack(jnp.broadcast_arrays(
        x1, y1, x2, y2), axis=-1)                                # [H,W,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return Tensor(boxes), Tensor(var)


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors -> (anchors, variances) [H, W, num_anchors, 4] in
    absolute pixel coords (reference: anchor_generator_op.h:60)."""
    in_arr = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    fh, fw = int(in_arr.shape[2]), int(in_arr.shape[3])
    sw, sh = float(stride[0]), float(stride[1])

    whs = []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            area = sw * sh
            base_w = round(math.sqrt(area / ar))
            base_h = round(base_w * ar)
            whs.append((size / sw * base_w, size / sh * base_h))
    aw = jnp.asarray([w for w, _ in whs], jnp.float32)           # [A]
    ah = jnp.asarray([h for _, h in whs], jnp.float32)

    xc = jnp.arange(fw, dtype=jnp.float32) * sw + offset * (sw - 1)
    yc = jnp.arange(fh, dtype=jnp.float32) * sh + offset * (sh - 1)
    x1 = xc[None, :, None] - 0.5 * (aw[None, None, :] - 1)
    y1 = yc[:, None, None] - 0.5 * (ah[None, None, :] - 1)
    x2 = xc[None, :, None] + 0.5 * (aw[None, None, :] - 1)
    y2 = yc[:, None, None] + 0.5 * (ah[None, None, :] - 1)
    anchors = jnp.stack(jnp.broadcast_arrays(x1, y1, x2, y2), axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return Tensor(anchors), Tensor(var)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0, name=None):
    """Encode/decode boxes against priors with variances (reference:
    box_coder_op.h EncodeCenterSize:41 / DecodeCenterSize:118).

    encode: target [N, 4], prior [M, 4] -> [N, M, 4]
    decode: target [N, M, 4], prior indexed by dim ``1-axis``'s
            counterpart (axis=0: prior per column M; axis=1: per row N)
            -> [N, M, 4]
    """
    pb = prior_box._data if isinstance(prior_box, Tensor) \
        else jnp.asarray(prior_box, jnp.float32)
    tb = target_box._data if isinstance(target_box, Tensor) \
        else jnp.asarray(target_box, jnp.float32)
    if prior_box_var is None:
        pbv = None
    elif isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)            # [4]
    else:
        pbv = prior_box_var._data if isinstance(prior_box_var, Tensor) \
            else jnp.asarray(prior_box_var, jnp.float32)         # [M, 4]

    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2

    if code_type.lower() in ("encode_center_size", "encode"):
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = (tb[:, 0] + tb[:, 2]) / 2
        tcy = (tb[:, 1] + tb[:, 3]) / 2
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
            jnp.log(jnp.abs(th[:, None] / ph[None, :])),
        ], axis=-1)                                              # [N, M, 4]
        if pbv is not None:
            out = out / (pbv if pbv.ndim == 1 else pbv[None, :, :])
        return Tensor(out)

    # decode
    if tb.ndim != 3:
        raise ValueError("decode_center_size expects target [N, M, 4]")
    ex = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
    d = tb
    if pbv is not None:
        v = pbv if pbv.ndim == 1 else ex(pbv)
        d = d * v
    cx = d[..., 0] * ex(pw) + ex(pcx)
    cy = d[..., 1] * ex(ph) + ex(pcy)
    w = jnp.exp(d[..., 2]) * ex(pw)
    h = jnp.exp(d[..., 3]) * ex(ph)
    out = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - norm, cy + h / 2 - norm], axis=-1)
    return Tensor(out)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold: float = 0.3, normalized: bool = True,
                   nms_eta: float = 1.0, background_label: int = 0,
                   name=None):
    """Per-class NMS + cross-class top-k (reference: multiclass_nms_op.cc
    kernel:199; layers/detection.py multiclass_nms).

    bboxes [N, M, 4], scores [N, C, M] -> (out [N, keep_top_k, 6]
    as (label, score, x1, y1, x2, y2) padded with -1, counts [N]).
    The reference returns a LoD tensor of ragged length; the TPU-native
    contract is the padded fixed-shape equivalent + valid counts.
    """
    bb = np.asarray(bboxes._data if isinstance(bboxes, Tensor) else bboxes,
                    np.float32)
    sc = np.asarray(scores._data if isinstance(scores, Tensor) else scores,
                    np.float32)
    N, C, M = sc.shape
    K = int(keep_top_k) if keep_top_k > 0 else M * C
    out = np.full((N, K, 6), -1.0, np.float32)
    counts = np.zeros((N,), np.int32)

    def _iou_matrix(b):
        # pure-numpy pairwise IoU (no device traffic in this host-side
        # post-op); +1 to w/h for unnormalized pixel boxes, per reference
        off = 0.0 if normalized else 1.0
        area = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
        lt = np.maximum(b[:, None, :2], b[None, :, :2])
        rb = np.minimum(b[:, None, 2:], b[None, :, 2:])
        wh = np.clip(rb - lt + off, 0, None)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area[:, None] + area[None, :] - inter + 1e-10)

    for n in range(N):
        iou = _iou_matrix(bb[n])       # [M, M], once per image
        dets = []                      # (score, label, box)
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            idx = np.nonzero(s > score_threshold)[0]
            if idx.size == 0:
                continue
            order = idx[np.argsort(-s[idx], kind="stable")]
            if nms_top_k > -1:
                order = order[:nms_top_k]
            kept: List[int] = []
            thr = float(nms_threshold)
            for i in order:
                if any(iou[i, j] > thr for j in kept):
                    continue
                kept.append(int(i))
                if nms_eta < 1.0 and thr > 0.5:
                    thr *= nms_eta
            dets.extend((float(s[i]), c, bb[n, i]) for i in kept)
        dets.sort(key=lambda d: -d[0])
        dets = dets[:K]
        counts[n] = len(dets)
        for k, (sv, c, box) in enumerate(dets):
            out[n, k, 0] = c
            out[n, k, 1] = sv
            out[n, k, 2:] = box
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(counts))
