"""Dependency-free ONNX graph decoder + numpy executor.

Exists so the exporter is VERIFIABLE in this environment (no `onnx` /
`onnxruntime` packages): tests decode the emitted ModelProto bytes with
the same wire rules and execute the graph with numpy, comparing against
the source model's outputs. It doubles as a reference consumer showing
the emitted files are structurally sound.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from . import proto

__all__ = ["OnnxModel", "load_model", "run_model"]

import ml_dtypes

_ONNX_TO_NP = {proto.FLOAT: np.float32, proto.INT32: np.int32,
               proto.INT64: np.int64, proto.BOOL: np.bool_,
               proto.DOUBLE: np.float64, proto.FLOAT16: np.float16,
               proto.BFLOAT16: np.dtype(ml_dtypes.bfloat16)}


def _string(v: bytes) -> str:
    return v.decode("utf-8")


def _parse_tensor(buf: bytes):
    f = proto.parse_message(buf)
    dims = [int(d) for d in f.get(1, [])]
    dtype = _ONNX_TO_NP[int(f[2][0])]
    name = _string(f[8][0])
    raw = f.get(9, [b""])[0]
    arr = np.frombuffer(raw, dtype=dtype).reshape(dims).copy()
    return name, arr


def _parse_attr(buf: bytes):
    f = proto.parse_message(buf)
    name = _string(f[1][0])
    atype = int(f.get(20, [0])[0])
    if atype == 1:                       # FLOAT
        import struct
        return name, struct.unpack("<f", f[2][0])[0]
    if atype == 2:                       # INT
        v = int(f[3][0])
        return name, v - (1 << 64) if v >= 1 << 63 else v
    if atype == 7:                       # INTS
        return name, [int(v) for v in f.get(8, [])]
    raise ValueError(f"attr {name}: unsupported type {atype}")


class _Node:
    def __init__(self, buf: bytes):
        f = proto.parse_message(buf)
        self.inputs = [_string(v) for v in f.get(1, [])]
        self.outputs = [_string(v) for v in f.get(2, [])]
        self.op = _string(f[4][0])
        self.attrs = dict(_parse_attr(a) for a in f.get(5, []))


class OnnxModel:
    def __init__(self, buf: bytes):
        m = proto.parse_message(buf)
        self.ir_version = int(m[1][0])
        g = proto.parse_message(m[7][0])
        self.graph_name = _string(g[2][0])
        self.nodes = [_Node(n) for n in g.get(1, [])]
        self.initializers = dict(_parse_tensor(t) for t in g.get(5, []))
        self.inputs = [self._vi_name(v) for v in g.get(11, [])]
        self.outputs = [self._vi_name(v) for v in g.get(12, [])]
        opset = proto.parse_message(m[8][0])
        self.opset = int(opset[2][0])

    @staticmethod
    def _vi_name(buf: bytes) -> str:
        return _string(proto.parse_message(buf)[1][0])


def load_model(path: str) -> OnnxModel:
    with open(path, "rb") as f:
        return OnnxModel(f.read())


def _np_conv(x, w, strides, pads, dilations, group):
    n_sp = x.ndim - 2
    pad_lo, pad_hi = pads[:n_sp], pads[n_sp:]
    x = np.pad(x, [(0, 0), (0, 0)] + [(lo, hi)
                                      for lo, hi in zip(pad_lo, pad_hi)])
    N, C = x.shape[:2]
    O, I = w.shape[:2]
    ks = w.shape[2:]
    eff = [(k - 1) * d + 1 for k, d in zip(ks, dilations)]
    out_sp = [(x.shape[2 + i] - eff[i]) // strides[i] + 1
              for i in range(n_sp)]
    out = np.zeros((N, O) + tuple(out_sp), np.float32)
    cg = C // group
    og = O // group
    for g in range(group):
        xs = x[:, g * cg:(g + 1) * cg]
        ws = w[g * og:(g + 1) * og]
        for idx in np.ndindex(*out_sp):
            starts = [idx[i] * strides[i] for i in range(n_sp)]
            sl = tuple(slice(starts[i], starts[i] + eff[i], dilations[i])
                       for i in range(n_sp))
            patch = xs[(slice(None), slice(None)) + sl]
            ax = list(range(1, patch.ndim))
            out[(slice(None), slice(g * og, (g + 1) * og)) + idx] = \
                np.tensordot(patch, ws, axes=(ax, ax))
    return out


def run_model(model: OnnxModel, feeds: Dict[str, np.ndarray]) -> List:
    env = dict(model.initializers)
    env.update({k: np.asarray(v) for k, v in feeds.items()})
    for node in model.nodes:
        i = [env[n] for n in node.inputs]
        op = node.op
        if op == "MatMul":
            out = np.matmul(i[0], i[1])
        elif op == "Add":
            out = i[0] + i[1]
        elif op == "Sub":
            out = i[0] - i[1]
        elif op == "Mul":
            out = i[0] * i[1]
        elif op == "Div":
            out = i[0] / i[1]
        elif op == "Pow":
            out = np.power(i[0], i[1])
        elif op == "Max":
            out = np.maximum(i[0], i[1])
        elif op == "Min":
            out = np.minimum(i[0], i[1])
        elif op in ("Exp", "Log", "Tanh", "Sqrt", "Abs", "Sign", "Floor",
                    "Ceil", "Sin", "Cos"):
            out = getattr(np, op.lower())(i[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-i[0]))
        elif op == "Erf":
            from math import erf
            out = np.vectorize(erf)(i[0]).astype(i[0].dtype)
        elif op == "Neg":
            out = -i[0]
        elif op == "Equal":
            out = i[0] == i[1]
        elif op == "Greater":
            out = i[0] > i[1]
        elif op == "Less":
            out = i[0] < i[1]
        elif op == "GreaterOrEqual":
            out = i[0] >= i[1]
        elif op == "LessOrEqual":
            out = i[0] <= i[1]
        elif op == "Transpose":
            out = np.transpose(i[0], node.attrs["perm"])
        elif op == "Reshape":
            out = i[0].reshape([int(d) for d in i[1]])
        elif op == "Expand":
            out = np.broadcast_to(i[0], [int(d) for d in i[1]]).copy()
        elif op in ("ReduceSum", "ReduceMax", "ReduceMin"):
            fn = {"ReduceSum": np.sum, "ReduceMax": np.max,
                  "ReduceMin": np.min}[op]
            # ReduceSum-13 carries axes as input; ReduceMax/Min-13 as attr
            if len(i) > 1:
                axes = tuple(int(a) for a in i[1])
            else:
                axes = tuple(node.attrs["axes"])
            out = fn(i[0], axis=axes,
                     keepdims=bool(node.attrs.get("keepdims", 1)))
        elif op == "Cast":
            out = i[0].astype(_ONNX_TO_NP[node.attrs["to"]])
        elif op == "Where":
            out = np.where(i[0].astype(bool), i[1], i[2])
        elif op == "Identity":
            out = i[0]
        elif op == "Shape":
            out = np.asarray(i[0].shape, np.int64)
        elif op == "Range":
            out = np.arange(int(np.asarray(i[0])),
                            int(np.asarray(i[1])),
                            int(np.asarray(i[2])),
                            dtype=np.asarray(i[0]).dtype)
        elif op == "Slice":
            starts, ends, axes, steps = (list(map(int, v)) for v in i[1:5])
            sl = [slice(None)] * i[0].ndim
            for st, en, ax, sp in zip(starts, ends, axes, steps):
                sl[ax] = slice(st, en, sp)
            out = i[0][tuple(sl)]
        elif op == "Gather":
            out = np.take(i[0], i[1].astype(np.int64),
                          axis=node.attrs.get("axis", 0))
        elif op == "Concat":
            out = np.concatenate(i, axis=node.attrs["axis"])
        elif op in ("MaxPool", "AveragePool"):
            ks = node.attrs["kernel_shape"]
            strides = node.attrs["strides"]
            pads = node.attrs["pads"]
            n_sp = len(ks)
            x = i[0]
            pad_lo, pad_hi = pads[:n_sp], pads[n_sp:]
            fill = -np.inf if op == "MaxPool" else 0.0
            x = np.pad(x, [(0, 0), (0, 0)] + list(zip(pad_lo, pad_hi)),
                       constant_values=fill)
            out_sp = [(x.shape[2 + k] - ks[k]) // strides[k] + 1
                      for k in range(n_sp)]
            out = np.zeros(x.shape[:2] + tuple(out_sp), np.float32)
            for idx in np.ndindex(*out_sp):
                sl = tuple(slice(idx[k] * strides[k],
                                 idx[k] * strides[k] + ks[k])
                           for k in range(n_sp))
                patch = x[(slice(None), slice(None)) + sl]
                red = patch.reshape(patch.shape[:2] + (-1,))
                if op == "MaxPool":
                    val = red.max(-1)
                elif node.attrs.get("count_include_pad", 0):
                    val = red.mean(-1)
                else:
                    raise NotImplementedError(
                        "AveragePool without count_include_pad")
                out[(slice(None), slice(None)) + idx] = val
        elif op == "Conv":
            out = _np_conv(i[0].astype(np.float32),
                           i[1].astype(np.float32),
                           node.attrs["strides"], node.attrs["pads"],
                           node.attrs["dilations"],
                           node.attrs.get("group", 1))
            if len(i) > 2:
                bias = i[2].reshape((1, -1) + (1,) * (out.ndim - 2))
                out = out + bias
        else:
            raise NotImplementedError(f"runtime op {op}")
        env[node.outputs[0]] = out
    return [env[n] for n in model.outputs]
