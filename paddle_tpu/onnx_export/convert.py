"""jaxpr -> ONNX graph conversion.

reference parity: paddle.onnx.export (reference: python/paddle/onnx/
export.py, delegating to paddle2onnx's program->ONNX op mappers).

TPU-native redesign: the model is traced to a jaxpr (the same IR every
jitted path uses) and each supported primitive maps to ONNX nodes —
`dot_general` to MatMul/Transpose compositions, `conv_general_dilated`
to Conv, elementwise/reduction/shape primitives to their operators,
pjit/custom_jvp sub-jaxprs inlined recursively. Unsupported primitives
raise, naming the culprit — a partial export is never silently wrong.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from . import proto

__all__ = ["jaxpr_to_onnx", "UnsupportedOnnxExport"]


class UnsupportedOnnxExport(NotImplementedError):
    pass


def _onnx_dtype(dtype) -> int:
    key = str(np.dtype(dtype)) if not str(dtype) in proto.NP_TO_ONNX \
        else str(dtype)
    try:
        return proto.NP_TO_ONNX[key]
    except KeyError:
        raise UnsupportedOnnxExport(
            f"dtype {dtype} has no ONNX mapping") from None


def _is_sym(d) -> bool:
    """A symbolic dimension (jax.export dim polynomial) vs a plain int."""
    return not isinstance(d, (int, np.integer))


class _Builder:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.counter = 0
        self.names: Dict[int, str] = {}   # id(jax var) -> onnx name
        self._literal_cache: Dict = {}
        # dynamic-batch support: symbol name -> (graph input name, axis)
        # so any shape operand containing the symbol resolves at RUNTIME
        # from Shape(input) — the dim_param contract
        self.sym_sources: Dict[str, tuple] = {}
        self._dim_cache: Dict[str, str] = {}

    def register_input_dims(self, name, shape):
        for ax, d in enumerate(shape):
            if _is_sym(d):
                self.sym_sources.setdefault(str(d), (name, ax))

    def dim_value(self, d) -> str:
        """int64[1] tensor holding a symbolic dim's runtime value."""
        key = str(d)
        if key in self._dim_cache:
            return self._dim_cache[key]
        src = self.sym_sources.get(key)
        if src is None:
            raise UnsupportedOnnxExport(
                f"symbolic dimension {d} does not appear in any graph "
                "input shape; dynamic dims must be tied to an input")
        in_name, ax = src
        shp = self.emit("Shape", [in_name])
        out = self.emit("Gather",
                        [shp, self.add_const(np.asarray([ax], np.int64))],
                        attributes=[proto.attr_int("axis", 0)])
        self._dim_cache[key] = out
        return out

    def shape_tensor(self, dims) -> str:
        """Name of an int64 1-D tensor holding `dims`: an initializer when
        fully static, a Concat of constants + runtime dim reads when any
        entry is symbolic."""
        dims = list(dims)
        if all(not _is_sym(d) for d in dims):
            return self.add_const(np.asarray([int(d) for d in dims],
                                             np.int64))
        parts: List[str] = []
        pending: List[int] = []

        def flush():
            if pending:
                parts.append(self.add_const(
                    np.asarray(pending, np.int64)))
                pending.clear()

        for d in dims:
            if _is_sym(d):
                flush()
                parts.append(self.dim_value(d))
            else:
                pending.append(int(d))
        flush()
        if len(parts) == 1:
            return parts[0]
        return self.emit("Concat", parts,
                         attributes=[proto.attr_int("axis", 0)])

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, var):
        from jax._src.core import Literal
        if isinstance(var, Literal):
            arr = np.asarray(var.val)
            ck = (str(arr.dtype), arr.shape, arr.tobytes())
            if ck not in self._literal_cache:
                self._literal_cache[ck] = self.add_const(arr)
            return self._literal_cache[ck]
        key = id(var)
        if key not in self.names:
            self.names[key] = self.fresh("v")
        return self.names[key]

    def add_const(self, arr: np.ndarray, hint="const"):
        name = self.fresh(hint)
        arr = np.asarray(arr)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        if arr.dtype == np.bool_:
            arr = arr.astype(np.int64)
        dt = _onnx_dtype(arr.dtype)
        self.initializers.append(proto.tensor_proto(
            name, arr.shape, dt, np.ascontiguousarray(arr).tobytes()))
        return name

    def emit(self, op, inputs, n_out=1, attributes=(), hint=None):
        outs = [self.fresh(hint or op.lower()) for _ in range(n_out)]
        self.nodes.append(proto.node_proto(op, inputs, outs,
                                           attributes=list(attributes)))
        return outs[0] if n_out == 1 else outs


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "neg": "Neg", "abs": "Abs",
    "sign": "Sign", "floor": "Floor", "ceil": "Ceil", "erf": "Erf",
    "sin": "Sin", "cos": "Cos",
    "eq": "Equal", "gt": "Greater", "lt": "Less",
    "ge": "GreaterOrEqual", "le": "LessOrEqual",
}

_REDUCE = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
           "reduce_min": "ReduceMin"}


def _handle_dot_general(b: _Builder, eqn, invals):
    dn = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dn
    lhs, rhs = eqn.invars
    l_nd, r_nd = len(lhs.aval.shape), len(rhs.aval.shape)
    lname, rname = invals
    if lc == (l_nd - 1,) and rc == (len(lb),) and r_nd == len(lb) + 2 \
            and lb == tuple(range(len(lb))) and \
            rb == tuple(range(len(rb))):
        # x[..., k] . w[*batch, k, n]: ONNX MatMul semantics directly
        # (rhs must be exactly batch+2-D, else the general path below)
        return b.emit("MatMul", [lname, rname])
    if not lb and not rb and lc == (l_nd - 1,) and rc == (r_nd - 1,) \
            and r_nd == 2:
        # x[..., k] . w[n, k]: transpose the weight then MatMul
        wt = b.emit("Transpose", [rname],
                    attributes=[proto.attr_ints("perm", [1, 0])])
        return b.emit("MatMul", [lname, wt])
    # general case: permute to [batch..., M, K] x [batch..., K, N],
    # flatten multi-dim frees/contractions, MatMul, reshape back.
    # dot_general's output order IS (batch, lhs_free, rhs_free).
    l_free = [d for d in range(l_nd) if d not in lc and d not in lb]
    r_free = [d for d in range(r_nd) if d not in rc and d not in rb]
    l_shape = lhs.aval.shape
    r_shape = rhs.aval.shape
    batch = [l_shape[d] for d in lb]       # may hold symbolic dims
    if any(_is_sym(l_shape[d]) for d in l_free + list(lc)) or \
            any(_is_sym(r_shape[d]) for d in r_free):
        raise UnsupportedOnnxExport(
            "dot_general with a symbolic free/contracting dim cannot "
            "flatten to MatMul (only batch dims may be dynamic)")
    M = int(np.prod([l_shape[d] for d in l_free])) if l_free else 1
    K = int(np.prod([l_shape[d] for d in lc]))
    N = int(np.prod([r_shape[d] for d in r_free])) if r_free else 1

    lp = b.emit("Transpose", [lname], attributes=[
        proto.attr_ints("perm", list(lb) + l_free + list(lc))])
    lp = b.emit("Reshape", [lp, b.shape_tensor(batch + [M, K])])
    rp = b.emit("Transpose", [rname], attributes=[
        proto.attr_ints("perm", list(rb) + list(rc) + r_free)])
    rp = b.emit("Reshape", [rp, b.shape_tensor(batch + [K, N])])
    mm = b.emit("MatMul", [lp, rp])
    out_shape = batch + [l_shape[d] for d in l_free] \
        + [r_shape[d] for d in r_free]
    return b.emit("Reshape", [mm, b.shape_tensor(out_shape)])


def _handle_conv(b: _Builder, eqn, invals):
    p = eqn.params
    dn = p["dimension_numbers"]
    if tuple(dn.lhs_spec) != tuple(range(len(dn.lhs_spec))) or \
            tuple(dn.rhs_spec) != tuple(range(len(dn.rhs_spec))):
        raise UnsupportedOnnxExport(
            "conv export supports NCHW/OIHW-style dimension specs only")
    if any(d != 1 for d in p.get("lhs_dilation", ())):
        raise UnsupportedOnnxExport("transposed conv export not supported")
    if p.get("batch_group_count", 1) != 1:
        raise UnsupportedOnnxExport(
            "conv with batch_group_count != 1 not supported")
    pads = [lo for lo, _ in p["padding"]] + [hi for _, hi in p["padding"]]
    attrs = [proto.attr_ints("strides", p["window_strides"]),
             proto.attr_ints("pads", pads),
             proto.attr_ints("dilations", p["rhs_dilation"]),
             proto.attr_int("group", p["feature_group_count"])]
    return b.emit("Conv", invals, attributes=attrs)


def _handle_gather(b: _Builder, eqn, invals):
    """Embedding-style gather (jnp.take along axis 0): operand [V, ...]
    indexed by integer ids -> ONNX Gather(axis=0). Other gather forms
    raise (the exporter's supported subset is explicit)."""
    dn = eqn.params["dimension_numbers"]
    operand = eqn.invars[0].aval
    ss = tuple(eqn.params["slice_sizes"])
    full_rest = tuple(operand.shape[1:])
    if tuple(dn.start_index_map) == (0,) and \
            tuple(dn.collapsed_slice_dims) == (0,) and \
            ss == (1,) + full_rest:
        idx_aval = eqn.invars[1].aval
        # indices arrive as [..., 1]; drop the trailing index-vector dim
        idx = invals[1]
        if idx_aval.shape and idx_aval.shape[-1] == 1:
            idx = b.emit("Reshape", [idx, b.shape_tensor(
                idx_aval.shape[:-1])])
        return b.emit("Gather", [invals[0], idx],
                      attributes=[proto.attr_int("axis", 0)])
    # single-position pick along one axis (e.g. the CLS select h[:, 0],
    # which lowers to this form under symbolic batch dims): indices are a
    # length-1 coordinate vector, every other axis is a full slice
    if len(dn.start_index_map) == 1:
        ax = dn.start_index_map[0]
        idx_aval = eqn.invars[1].aval
        full_others = all(
            (i == ax and s == 1) or
            (i != ax and (s == operand.shape[i]))
            for i, s in enumerate(ss))
        if dn.collapsed_slice_dims == (ax,) and full_others and \
                tuple(idx_aval.shape) == (1,) and \
                tuple(dn.offset_dims) == tuple(
                    range(len(operand.shape) - 1)):
            scalar = b.emit("Reshape", [
                invals[1], b.add_const(np.asarray([], np.int64))])
            return b.emit("Gather", [invals[0], scalar],
                          attributes=[proto.attr_int("axis", ax)])
    raise UnsupportedOnnxExport(
        f"gather with dimension_numbers {dn} / slice_sizes {ss} has no "
        "ONNX mapping (only axis-0 embedding-style gathers and "
        "single-position axis picks export)")


def _inner_closed(eqn):
    for key in ("call_jaxpr", "jaxpr"):
        inner = eqn.params.get(key)
        if inner is not None:
            if hasattr(inner, "jaxpr"):      # ClosedJaxpr
                return inner.jaxpr, list(inner.consts)
            return inner, []
    return None, None


def _convert_eqns(b: _Builder, eqns):
    for eqn in eqns:
        prim = eqn.primitive.name
        if prim in ("pjit", "jit", "custom_jvp_call", "custom_vjp_call",
                    "closed_call", "core_call", "xla_call",
                    "remat", "checkpoint", "remat2"):
            ij, consts = _inner_closed(eqn)
            if ij is None:
                raise UnsupportedOnnxExport(f"{prim} without inner jaxpr")
            invals = [b.name_of(v) for v in eqn.invars]
            for cv, ca in zip(ij.constvars, consts):
                b.names[id(cv)] = b.add_const(np.asarray(ca), hint="c")
            for iv, nm in zip(ij.invars, invals):
                b.names[id(iv)] = nm
            _convert_eqns(b, ij.eqns)
            for outer_ov, ov in zip(eqn.outvars, ij.outvars):
                b.names[id(outer_ov)] = b.name_of(ov)
            continue

        invals = [b.name_of(v) for v in eqn.invars]
        if prim in _ELEMENTWISE:
            out = b.emit(_ELEMENTWISE[prim], invals)
        elif prim == "erfc":
            e = b.emit("Erf", invals)
            one = b.add_const(np.asarray(
                1.0, np.dtype(eqn.invars[0].aval.dtype)))
            out = b.emit("Sub", [one, e])
        elif prim == "square":
            out = b.emit("Mul", [invals[0], invals[0]])
        elif prim == "slice":
            starts = eqn.params["start_indices"]
            limits = eqn.params["limit_indices"]
            strides = eqn.params["strides"] or [1] * len(starts)
            axes = list(range(len(starts)))
            if any(_is_sym(s) for s in starts):
                raise UnsupportedOnnxExport(
                    "slice with symbolic start indices")
            in_shape = eqn.invars[0].aval.shape
            # a symbolic limit exports only as "to the end of that dim"
            # (ONNX Slice clamps INT64_MAX); a partial symbolic limit
            # (e.g. first half of a 2*batch axis) must raise, not
            # silently export full-length
            fixed = []
            for ax2, e in enumerate(limits):
                if not _is_sym(e):
                    fixed.append(int(e))
                elif e == in_shape[ax2]:
                    fixed.append(1 << 62)
                else:
                    raise UnsupportedOnnxExport(
                        f"slice with symbolic limit {e} != dim "
                        f"{in_shape[ax2]} has no ONNX mapping")
            limits = fixed
            out = b.emit("Slice", [
                invals[0],
                b.add_const(np.asarray(starts, np.int64)),
                b.add_const(np.asarray(limits, np.int64)),
                b.add_const(np.asarray(axes, np.int64)),
                b.add_const(np.asarray(strides, np.int64))])
        elif prim == "gather":
            out = _handle_gather(b, eqn, invals)
        elif prim == "iota":
            shape = eqn.outvars[0].aval.shape
            d = eqn.params["dimension"]
            np_dt = np.dtype(eqn.outvars[0].aval.dtype)
            if all(not _is_sym(s) for s in shape):
                # static shape: bake the index grid as an initializer
                view = [1] * len(shape)
                view[d] = shape[d]
                grid = np.broadcast_to(
                    np.arange(shape[d]).reshape(view), shape)
                out = b.add_const(
                    np.ascontiguousarray(grid).astype(np_dt))
            else:
                # dynamic dims: Range along the iota axis (runtime length
                # when symbolic), reshaped to the 1-padded view and
                # Expanded to the runtime shape
                if _is_sym(shape[d]):
                    n = b.emit("Reshape", [b.dim_value(shape[d]),
                                           b.add_const(
                                               np.asarray([], np.int64))])
                    rng = b.emit("Range", [
                        b.add_const(np.asarray(0, np.int64)), n,
                        b.add_const(np.asarray(1, np.int64))])
                else:
                    rng = b.add_const(np.arange(shape[d], dtype=np.int64))
                view = [1] * len(shape)
                view[d] = shape[d]
                mid = b.emit("Reshape", [rng, b.shape_tensor(view)])
                out = b.emit("Expand", [mid, b.shape_tensor(shape)])
                if np_dt != np.int64:
                    out = b.emit("Cast", [out], attributes=[
                        proto.attr_int("to", _onnx_dtype(np_dt))])
        elif prim == "rsqrt":
            s = b.emit("Sqrt", invals)
            one = b.add_const(np.asarray(
                1.0, np.dtype(eqn.invars[0].aval.dtype)))
            out = b.emit("Div", [one, s])
        elif prim == "integer_pow":
            e = b.add_const(np.asarray(
                float(eqn.params["y"]),
                np.dtype(eqn.invars[0].aval.dtype)))
            out = b.emit("Pow", [invals[0], e])
        elif prim == "dot_general":
            out = _handle_dot_general(b, eqn, invals)
        elif prim == "conv_general_dilated":
            out = _handle_conv(b, eqn, invals)
        elif prim in ("reshape", "squeeze", "expand_dims"):
            out = b.emit("Reshape", [
                invals[0], b.shape_tensor(eqn.outvars[0].aval.shape)])
        elif prim == "transpose":
            out = b.emit("Transpose", invals, attributes=[
                proto.attr_ints("perm", eqn.params["permutation"])])
        elif prim == "broadcast_in_dim":
            tgt = eqn.outvars[0].aval.shape
            bdims = eqn.params["broadcast_dimensions"]
            in_shape = eqn.invars[0].aval.shape
            inter = [1] * len(tgt)
            for i, d in enumerate(bdims):
                inter[d] = in_shape[i]
            if tuple(eqn.invars[0].aval.shape) == ():
                inter = [1] * max(len(tgt), 1)
            mid = b.emit("Reshape", [invals[0], b.shape_tensor(inter)])
            shp = b.shape_tensor(tgt if tgt else (1,))
            out = b.emit("Expand", [mid, shp])
            if not tgt:
                out = b.emit("Reshape", [out, b.add_const(
                    np.asarray([], np.int64))])
        elif prim == "reduce_sum":
            # ReduceSum-13 takes axes as an INPUT
            axes = b.add_const(np.asarray(eqn.params["axes"], np.int64))
            out = b.emit("ReduceSum", [invals[0], axes], attributes=[
                proto.attr_int("keepdims", 0)])
        elif prim in ("reduce_max", "reduce_min"):
            # ReduceMax/Min-13 take axes as an ATTRIBUTE (input form is
            # opset 18+)
            out = b.emit(_REDUCE[prim], [invals[0]], attributes=[
                proto.attr_ints("axes", eqn.params["axes"]),
                proto.attr_int("keepdims", 0)])
        elif prim == "convert_element_type":
            tdt = _onnx_dtype(eqn.params["new_dtype"])
            out = b.emit("Cast", invals,
                         attributes=[proto.attr_int("to", tdt)])
        elif prim == "select_n":
            if len(invals) != 3:
                raise UnsupportedOnnxExport(
                    f"select_n with {len(invals) - 1} cases (only the "
                    "binary predicate form maps to ONNX Where)")
            cond = b.emit("Cast", [invals[0]], attributes=[
                proto.attr_int("to", proto.BOOL)])
            out = b.emit("Where", [cond, invals[2], invals[1]])
        elif prim in ("stop_gradient", "copy"):
            out = b.emit("Identity", invals)
        elif prim in ("reduce_window_max", "reduce_window_sum"):
            # pooling windows over NCHW: window/strides are all-1 on the
            # leading batch/channel dims
            wd = eqn.params["window_dimensions"]
            ws = eqn.params["window_strides"]
            pad = eqn.params["padding"]
            wdl = eqn.params.get("window_dilation",
                                 (1,) * len(wd))
            bdl = eqn.params.get("base_dilation", (1,) * len(wd))
            if tuple(wd[:2]) != (1, 1) or tuple(ws[:2]) != (1, 1) or \
                    any(p_ != (0, 0) for p_ in pad[:2]) or \
                    any(d != 1 for d in wdl) or \
                    any(d != 1 for d in bdl):
                raise UnsupportedOnnxExport(
                    "reduce_window export needs plain NCHW pooling "
                    "windows (no dilation, no batch/channel padding)")
            kwargs = [proto.attr_ints("kernel_shape", wd[2:]),
                      proto.attr_ints("strides", ws[2:]),
                      proto.attr_ints("pads",
                                      [lo for lo, _ in pad[2:]]
                                      + [hi for _, hi in pad[2:]])]
            if prim == "reduce_window_max":
                out = b.emit("MaxPool", [invals[0]], attributes=kwargs)
            else:
                # sum window = AveragePool * window_size;
                # count_include_pad=1 so padded borders divide by the FULL
                # window (matching the sum semantics)
                kwargs = kwargs + [proto.attr_int("count_include_pad", 1)]
                out = b.emit("AveragePool", [invals[0]], attributes=kwargs)
                scale = b.add_const(np.asarray(
                    float(np.prod(wd)),
                    np.dtype(eqn.invars[0].aval.dtype)))
                out = b.emit("Mul", [out, scale])
        elif prim == "concatenate":
            out = b.emit("Concat", invals, attributes=[
                proto.attr_int("axis", eqn.params["dimension"])])
        else:
            raise UnsupportedOnnxExport(
                f"primitive {prim!r} has no ONNX mapping; supported: "
                f"{sorted(_ELEMENTWISE)} + dot_general/"
                "conv_general_dilated/reshape/transpose/broadcast_in_dim/"
                "reduce_(sum|max|min)/convert_element_type/select_n/"
                "concatenate (+ pjit/custom-call inlining)")
        b.names[id(eqn.outvars[0])] = out
        if len(eqn.outvars) > 1:
            raise UnsupportedOnnxExport(
                f"multi-output primitive {prim!r} unsupported")


def jaxpr_to_onnx(closed_jaxpr, input_names, consts, graph_name="model",
                  opset=13):
    """Convert a closed jaxpr to ONNX ModelProto bytes.

    input_names: names for the leading jaxpr invars that are GRAPH
    inputs (same order); remaining invars are weights whose arrays come
    from `consts` (aligned) and become initializers.
    """
    jaxpr = closed_jaxpr.jaxpr
    b = _Builder()

    def vi_shape(shape):
        return [str(d) if _is_sym(d) else int(d) for d in shape]

    graph_inputs = []
    for var, name in zip(jaxpr.invars[:len(input_names)], input_names):
        b.names[id(var)] = name
        b.register_input_dims(name, var.aval.shape)
        dt = _onnx_dtype(var.aval.dtype)
        graph_inputs.append(proto.value_info(name, dt,
                                             vi_shape(var.aval.shape)))
    for var, arr in zip(jaxpr.invars[len(input_names):], consts):
        b.names[id(var)] = b.add_const(np.asarray(arr), hint="w")
    for var, arr in zip(jaxpr.constvars, closed_jaxpr.consts):
        b.names[id(var)] = b.add_const(np.asarray(arr), hint="c")

    _convert_eqns(b, jaxpr.eqns)

    graph_outputs = []
    for var in jaxpr.outvars:
        nm = b.name_of(var)
        dt = _onnx_dtype(var.aval.dtype)
        graph_outputs.append(proto.value_info(nm, dt,
                                              vi_shape(var.aval.shape)))

    graph = proto.graph_proto(b.nodes, graph_name, b.initializers,
                              graph_inputs, graph_outputs)
    return proto.model_proto(graph, opset_version=opset)
