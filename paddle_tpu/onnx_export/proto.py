"""Minimal protobuf wire-format encoder/decoder for ONNX messages.

The image ships no `onnx` package, so the exporter emits the wire bytes
directly against the onnx.proto schema (field numbers below mirror
https://github.com/onnx/onnx/blob/main/onnx/onnx.proto). The decoder
exists so tests can round-trip and EXECUTE exported graphs without any
external dependency.

Wire format: each field is a varint key ``(field_number << 3) | type``
with type 0 = varint, 2 = length-delimited, 5 = 32-bit.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

# ---------------------------------------------------------------------------
# low-level wire helpers
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(int(value))


def field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def field_string(num: int, s: str) -> bytes:
    return field_bytes(num, s.encode("utf-8"))


def field_float(num: int, value: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", float(value))


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse_message(buf: bytes) -> Dict[int, List]:
    """Decode one message into {field_number: [raw values]} — varints as
    ints, length-delimited as bytes, 32-bit as raw 4 bytes."""
    fields: Dict[int, List] = {}
    pos = 0
    while pos < len(buf):
        key, pos = read_varint(buf, pos)
        num, wtype = key >> 3, key & 7
        if wtype == 0:
            val, pos = read_varint(buf, pos)
        elif wtype == 2:
            ln, pos = read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        fields.setdefault(num, []).append(val)
    return fields


# ---------------------------------------------------------------------------
# ONNX message field numbers (onnx.proto)
# ---------------------------------------------------------------------------

# TensorProto.DataType
FLOAT, INT32, INT64, BOOL, FLOAT16, BFLOAT16, DOUBLE = 1, 6, 7, 9, 10, 16, 11

NP_TO_ONNX = {
    "float32": FLOAT, "int32": INT32, "int64": INT64, "bool": BOOL,
    "float16": FLOAT16, "bfloat16": BFLOAT16, "float64": DOUBLE,
}


def tensor_proto(name: str, dims, data_type: int, raw: bytes) -> bytes:
    out = b"".join(field_varint(1, d) for d in dims)
    out += field_varint(2, data_type)
    out += field_string(8, name)
    out += field_bytes(9, raw)
    return out


def attr_int(name: str, value: int) -> bytes:
    return field_string(1, name) + field_varint(3, value) \
        + field_varint(20, 2)                     # AttributeProto.INT


def attr_float(name: str, value: float) -> bytes:
    return field_string(1, name) + field_float(2, value) \
        + field_varint(20, 1)                     # AttributeProto.FLOAT


def attr_ints(name: str, values) -> bytes:
    out = field_string(1, name)
    for v in values:
        out += field_varint(8, v)
    out += field_varint(20, 7)                    # AttributeProto.INTS
    return out


def node_proto(op_type: str, inputs, outputs, name: str = "",
               attributes=()) -> bytes:
    """attributes: iterable of encoded AttributeProto payloads."""
    out = b"".join(field_string(1, i) for i in inputs)
    out += b"".join(field_string(2, o) for o in outputs)
    if name:
        out += field_string(3, name)
    out += field_string(4, op_type)
    out += b"".join(field_bytes(5, a) for a in attributes)
    return out


def value_info(name: str, elem_type: int, shape) -> bytes:
    """String dims encode as ``dim_param`` (symbolic, e.g. a dynamic
    batch axis — onnx.proto TensorShapeProto.Dimension field 2);
    integers as ``dim_value``."""
    dims = b""
    for d in shape:
        if isinstance(d, str):
            dims += field_bytes(1, field_string(2, d))
        else:
            dims += field_bytes(1, field_varint(1, int(d)))
    shape_proto = dims
    tensor_type = field_varint(1, elem_type) + field_bytes(2, shape_proto)
    type_proto = field_bytes(1, tensor_type)
    return field_string(1, name) + field_bytes(2, type_proto)


def graph_proto(nodes, name, initializers, inputs, outputs) -> bytes:
    out = b"".join(field_bytes(1, n) for n in nodes)
    out += field_string(2, name)
    out += b"".join(field_bytes(5, t) for t in initializers)
    out += b"".join(field_bytes(11, i) for i in inputs)
    out += b"".join(field_bytes(12, o) for o in outputs)
    return out


def model_proto(graph: bytes, opset_version: int = 13,
                producer: str = "paddle_tpu") -> bytes:
    opset = field_string(1, "") + field_varint(2, opset_version)
    out = field_varint(1, 8)                      # ir_version 8
    out += field_string(2, producer)
    out += field_bytes(7, graph)
    out += field_bytes(8, opset)
    return out
