"""Real ONNX export: jaxpr tracing -> hand-emitted ModelProto bytes.

reference parity: paddle.onnx.export (python/paddle/onnx/export.py via
paddle2onnx). The image ships no onnx package, so the wire bytes are
emitted directly (proto.py) and a bundled numpy runtime (runtime.py)
decodes + executes exported graphs for dependency-free verification.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .convert import UnsupportedOnnxExport, jaxpr_to_onnx
from .runtime import OnnxModel, load_model, run_model

__all__ = ["export", "UnsupportedOnnxExport", "OnnxModel", "load_model",
           "run_model"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 13, **configs) -> str:
    """Export a Layer (or callable over Tensors) to `<path>.onnx`.

    The forward is traced to a jaxpr in eval mode and converted to ONNX
    nodes; parameters/buffers become initializers. Models using
    primitives without a mapping raise UnsupportedOnnxExport naming the
    primitive (the flash-attention kernels and other custom calls are in
    that set — export runs the pure-XLA paths).
    """
    import jax
    import jax.numpy as jnp

    from ..core.random import trace_rng
    from ..core.tensor import Tensor, no_grad
    from ..jit.functional import bind, buffer_arrays, param_arrays, unwrap
    from ..jit.input_spec import InputSpec
    from ..nn.layer import Layer

    if input_spec is None:
        raise ValueError("onnx export needs input_spec (shapes/dtypes)")
    if opset_version < 13:
        raise ValueError(
            f"opset_version={opset_version}: this exporter emits opset-13 "
            "constructs (ReduceSum axes input, GreaterOrEqual, ...); use "
            ">= 13")
    if configs:
        raise ValueError(
            f"unsupported ONNX export options: {sorted(configs)}")
    specs = [s if isinstance(s, InputSpec) else InputSpec(s)
             for s in input_spec]
    # None/-1 dims become jax.export symbolic dimensions in ONE shared
    # scope (all inputs' batch axes must co-vary) and export as
    # ``dim_param`` symbols; shape operands touching them lower to
    # runtime Shape/Gather/Concat subgraphs (convert.py shape_tensor)
    dynamic = any(d is None or d < 0 for s_ in specs for d in s_.shape)
    if dynamic:
        from jax import export as jexport
        scope = jexport.SymbolicScope()

        def dim(i, ax, d):
            if d is not None and d >= 0:
                return str(int(d))
            return "batch" if ax == 0 else f"dyn_{i}_{ax}"

        shapes = [jexport.symbolic_shape(
            ", ".join(dim(i, ax, d) for ax, d in enumerate(s_.shape)),
            scope=scope) for i, s_ in enumerate(specs)]
        example = [jax.ShapeDtypeStruct(shp, s_.dtype)
                   for shp, s_ in zip(shapes, specs)]
    else:
        example = [jnp.zeros(tuple(s.shape), s.dtype) for s in specs]

    # the ONNX op set has no lax.scan/while analogue in this converter:
    # trace transformer stacks in their unrolled loop layout and losses in
    # their dense (non-streamed) composition — both are internal trace-time
    # layouts (nn/scan.py, nn/chunked_ce.py), so forcing them here changes
    # nothing about the exported model's weights/semantics
    from ..core.flags import flag_scope

    with flag_scope("scan_layers", False), \
            flag_scope("chunked_ce_threshold", 0):
        if isinstance(layer, Layer):
            was_training = layer.training
            layer.eval()
            params = param_arrays(layer)
            buffers = buffer_arrays(layer)
            flat_params = list(params.values()) + list(buffers.values())

            # key hoisted OUT of the traced fn: creating it inside would
            # record random_seed/random_wrap primitives even though
            # eval-mode forwards never consume randomness
            _key = jax.random.key(0)

            def fn(*all_args):
                inputs = all_args[:len(example)]
                pvals = all_args[len(example):len(example) + len(params)]
                bvals = all_args[len(example) + len(params):]
                p = dict(zip(params.keys(), pvals))
                bufs = dict(zip(buffers.keys(), bvals))
                with bind(layer, p, bufs), no_grad(), trace_rng(_key):
                    out = layer(*[Tensor(i) for i in inputs])
                return unwrap(out)

            try:
                closed = jax.make_jaxpr(fn)(*example, *flat_params)
            finally:
                if was_training:
                    layer.train()
            consts = flat_params
        else:
            _key = jax.random.key(0)

            def fn(*inputs):
                with no_grad(), trace_rng(_key):
                    out = layer(*[Tensor(i) for i in inputs])
                return unwrap(out)

            closed = jax.make_jaxpr(fn)(*example)
            consts = []

    names = [f"x{i}" for i in range(len(example))]
    data = jaxpr_to_onnx(closed, names, consts,
                         graph_name=type(layer).__name__,
                         opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(data)
    return out_path
