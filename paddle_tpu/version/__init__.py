"""paddle.version (reference: generated python/paddle/version.py)."""

full_version = "0.3.0"
major = "0"
minor = "3"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"
cuda_version = "None"        # the accelerator is a TPU
cudnn_version = "None"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
