"""paddle_tpu.static — static-graph compatibility facade.

The reference's static mode (ProgramDesc + Executor, reference:
python/paddle/fluid/framework.py Program:4392, executor.py:1065) maps onto
jit tracing here: a "Program" is a traced pure function; the "Executor" jit
compiles and runs it. This module offers the paddle.static surface for
users migrating static-graph code; new code should use paddle_tpu.jit.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core import dtypes
from ..core.tensor import Tensor
from ..jit.input_spec import InputSpec

_static_mode = [False]


def _enable_static_mode():
    _static_mode[0] = True


def _in_static_mode():
    return _static_mode[0]


class Program:
    """A recorded pure function over named inputs (ProgramDesc analogue)."""

    def __init__(self):
        self._build_fn = None  # set by program_guard recording
        self._inputs: Dict[str, InputSpec] = {}
        self._fetch: List = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m = _default_main[0]
    prev_s = _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0] = prev_m
        _default_startup[0] = prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference: fluid/data.py). In eager-first mode
    this returns a zero placeholder Tensor tagged with its name."""
    shape = tuple(1 if (d is None or d < 0) else d for d in shape)
    t = Tensor(np.zeros(shape, np.dtype(dtypes.convert_dtype(dtype))))
    t.name = name
    return t


class Executor:
    """Compatibility Executor: runs a python callable as the 'program'.

    For real static-style training use paddle_tpu.jit.TrainStep — this class
    exists so `exe.run(feed=..., fetch_list=...)` code keeps a familiar shape.
    """

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        if callable(program):
            out = program(**(feed or {}))
            outs = out if isinstance(out, (list, tuple)) else [out]
            if return_numpy:
                return [np.asarray(o.data) if isinstance(o, Tensor) else np.asarray(o)
                        for o in outs]
            return list(outs)
        raise TypeError(
            "paddle_tpu.static.Executor runs python callables; build models "
            "eagerly and use jit.TrainStep for compiled training.")


# nn facade for static-style layer helpers
class _StaticNN:
    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from ..nn import Linear
        from ..nn import functional as F
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        layer = Linear(in_dim, size)
        from ..tensor.manipulation import reshape
        flat = reshape(x, tuple(x.shape[:num_flatten_dims]) + (in_dim,))
        out = layer(flat)
        if activation:
            out = getattr(F, activation)(out)
        return out


nn = _StaticNN()
