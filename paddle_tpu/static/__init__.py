"""paddle_tpu.static — static-graph compatibility facade.

The reference's static mode (ProgramDesc + Executor, reference:
python/paddle/fluid/framework.py Program:4392, executor.py:1065) maps onto
jit tracing here: a "Program" is a traced pure function; the "Executor" jit
compiles and runs it. This module offers the paddle.static surface for
users migrating static-graph code; new code should use paddle_tpu.jit.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core import dtypes
from ..core.tensor import Tensor
from ..jit.input_spec import InputSpec

from . import nn  # noqa: F401,E402  (functional control flow: cond/while_loop)

_static_mode = [False]


def _enable_static_mode():
    _static_mode[0] = True


def _in_static_mode():
    return _static_mode[0]


class Program:
    """A RECORDED op graph (ProgramDesc analogue).

    Building: inside ``program_guard(main)``, `static.data` placeholders
    and every op dispatched through the framework (core.tensor.apply) are
    appended to this program — the eager execution doubles as the build
    pass (the reference traces ops into blocks the same way,
    framework.py Program/Block append_op). Running: `Executor.run`
    replays the recorded ops as ONE jit-compiled pure function over
    (feeds, parameters), with `optimizer.minimize` turning the replay
    into a fused grad+update train step.
    """

    def __init__(self):
        self._inputs: Dict[str, InputSpec] = {}
        self._fetch: List = []
        self.random_seed = 0
        # recorded graph state
        self._ops: List = []            # (fn, name, static_kw, in_spec, out_ids)
        self._placeholders: Dict[str, Tensor] = {}
        self._tensors: Dict[int, Tensor] = {}   # keep intermediates alive
        self._params: Dict[int, Tensor] = {}
        self._optimizer = None
        self._loss = None
        self._run_cache: Dict = {}
        self._mutated: List[int] = []   # buffer ids written during build
        self._test_variants: Dict[int, object] = {}  # op idx -> eval twin

    # -- recording (called by core.tensor.apply) ------------------------
    def _record_op(self, fn, name, static_kw, args, result):
        in_spec = []
        for a in args:
            if isinstance(a, Tensor):
                self._tensors[id(a)] = a
                from ..core.tensor import Parameter
                if isinstance(a, Parameter) or getattr(a, "persistable",
                                                       False):
                    self._params[id(a)] = a
                in_spec.append(("t", id(a)))
            else:
                in_spec.append(("c", a))
        outs = result if isinstance(result, (tuple, list)) else [result]
        out_ids = []
        for o in outs:
            if isinstance(o, Tensor):
                self._tensors[id(o)] = o
                out_ids.append(id(o))
            else:
                out_ids.append(None)
        self._ops.append((fn, name, static_kw, in_spec, out_ids))

    def _annotate_test_variant(self, test_fn):
        """Register an eval-mode twin for the most recently recorded op
        (core.tensor.annotate_test_variant)."""
        if self._ops:
            self._test_variants[len(self._ops) - 1] = test_fn

    def _record_write(self, target, src):
        """Record an in-place state write (core.tensor.record_mutation):
        from here on, reads of ``target`` resolve to ``src``'s value, and
        Executor.run writes the final value back to the live Tensor — BN
        running stats train under Executor.run exactly as the reference's
        (executor.cc:170 runs the stat-update ops of the ProgramDesc)."""
        self._tensors[id(target)] = target
        self._tensors[id(src)] = src
        self._ops.append((None, "__write__", None,
                          [("t", id(src))], [id(target)]))
        if id(target) not in self._mutated:
            self._mutated.append(id(target))

    def add_placeholder(self, name, tensor):
        self._placeholders[name] = tensor
        self._tensors[id(tensor)] = tensor

    def _replay(self, env):
        """Execute recorded ops over env: {tensor_id: array}. Returns env
        (mutated). Values not in env resolve to their recorded arrays."""
        for fn, name, static_kw, in_spec, out_ids in self._ops:
            vals = [(env[v] if v in env else self._tensors[v]._data)
                    if kind == "t" else v
                    for kind, v in in_spec]
            if name == "__write__":       # buffer write: alias, no compute
                # state is never a gradient path: cut here so a read-after-
                # write (QAT scales) can't backprop through the update
                env[out_ids[0]] = jax.lax.stop_gradient(vals[0])
                continue
            out = fn(*vals, **static_kw) if static_kw else fn(*vals)
            outs = out if isinstance(out, (tuple, list)) else [out]
            for oid, o in zip(out_ids, outs):
                if oid is not None:
                    env[oid] = o
        return env

    def leaf_ids(self):
        """Tensor inputs that are neither op outputs nor placeholders:
        parameters, buffers, captured constants. Passed FRESH into every
        replay so state reads are never baked as trace constants.

        Order-aware: an id read BEFORE any op (or write event) produces it
        is a leaf even if later overwritten — a BN running-stat buffer is
        both a leaf (its pre-step value feeds the normalization) and a
        write target (its post-step value is fetched back)."""
        produced = set()
        ph = {id(t) for t in self._placeholders.values()}
        leaves = []
        for fn, name, static_kw, in_spec, out_ids in self._ops:
            for kind, v in in_spec:
                if kind == "t" and v not in produced and v not in ph:
                    leaves.append(v)
            produced.update(o for o in out_ids if o is not None)
        return sorted(set(leaves))

    def global_block(self):
        return self

    def clone(self, for_test=False):
        """Copy this program. ``for_test=True`` converts it to inference
        form (reference: framework.py Program.clone(for_test=True), which
        flips ops' is_test attributes): train-only ops (BN batch-stat
        normalization, dropout, QAT range tracking) are swapped for their
        recorded eval twins, state-write events are stripped, and the
        optimizer/loss attachment is dropped."""
        import copy
        out = copy.copy(self)
        out._run_cache = {}
        if not for_test:
            return out
        out._ops = []
        out._test_variants = {}
        for i, (fn, name, static_kw, in_spec, out_ids) in \
                enumerate(self._ops):
            if name == "__write__":
                continue                       # no state mutation at eval
            twin = self._test_variants.get(i)
            if twin is not None:
                fn = twin
                name = name + "__test"
            out._ops.append((fn, name, static_kw, in_spec, out_ids))
        out._mutated = []
        out._optimizer = None
        out._loss = None
        return out


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Route `static.data` and ALL dispatched ops into `main_program`
    (reference: fluid/framework.py program_guard): the eager build pass
    records a replayable graph."""
    from ..core.tensor import pop_static_recorder, push_static_recorder
    prev_m = _default_main[0]
    prev_s = _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    push_static_recorder(main_program)
    try:
        yield
    finally:
        pop_static_recorder()
        _default_main[0] = prev_m
        _default_startup[0] = prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference: fluid/data.py): a placeholder
    Tensor (zeros at build time; None dims become 1) registered with the
    active recording program so Executor.run can substitute feeds."""
    shape = tuple(1 if (d is None or d < 0) else d for d in shape)
    t = Tensor(np.zeros(shape, np.dtype(dtypes.convert_dtype(dtype))))
    t.name = name
    prog = _default_main[0]
    if prog is not None:
        prog.add_placeholder(name, t)
    return t


class CompiledProgram:
    """A jit-compiled pure function over named feeds (the working analogue
    of the reference's CompiledProgram, compiler.py). Built from a python
    callable; the Executor compiles once per feed signature and caches."""

    def __init__(self, fn):
        self.fn = fn
        self._cache = {}

    def _run(self, feed: Dict):
        names = tuple(sorted(feed))
        arrs = {k: (v._data if isinstance(v, Tensor)
                    else jax.numpy.asarray(v)) for k, v in feed.items()}
        sig = (names, tuple((tuple(a.shape), str(a.dtype))
                            for a in (arrs[n] for n in names)))
        jitted = self._cache.get(sig)
        if jitted is None:
            def pure(kw):
                out = self.fn(**{k: Tensor(v) for k, v in kw.items()})
                outs = out if isinstance(out, (list, tuple)) else [out]
                return [o._data if isinstance(o, Tensor) else o
                        for o in outs]
            jitted = jax.jit(pure)
            self._cache[sig] = jitted
        return jitted(arrs)


class Executor:
    """Executor over callables / CompiledProgram.

    The reference executes serialized ProgramDescs (executor.py:1065); the
    TPU-native 'program' is a traceable python callable — `run` jit
    compiles it (cached per feed signature) and fetches numpy results. For
    training loops prefer paddle_tpu.jit.TrainStep (donated buffers,
    optimizer fused into the step).
    """

    def __init__(self, place=None):
        self.place = place
        self._compiled: Dict[int, CompiledProgram] = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if program is None:
            program = default_main_program()
        if isinstance(program, Program):
            outs = self._run_recorded(program, feed or {}, fetch_list or [])
        elif isinstance(program, CompiledProgram):
            outs = program._run(feed or {})
        elif callable(program):
            # memoize per callable: repeated exe.run(fn, ...) hits the same
            # jit cache instead of retracing+recompiling every call
            cp = self._compiled.get(id(program))
            if cp is None or cp.fn is not program:
                cp = CompiledProgram(program)
                self._compiled[id(program)] = cp
            outs = cp._run(feed or {})
        else:
            raise TypeError(
                "paddle_tpu.static.Executor runs python callables or "
                "CompiledProgram (the TPU-native 'program'); legacy "
                "ProgramDesc graphs do not exist in this framework — build "
                "models eagerly and use jit.TrainStep for compiled "
                "training.")
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_recorded(self, program: Program, feed, fetch_list):
        """Replay a recorded Program as one jitted pure function over
        (feeds, params); with an attached optimizer, also compute grads
        and apply the update (the reference's static training loop)."""
        if not program._ops:
            if fetch_list:
                raise ValueError(
                    "this Program has no recorded ops — build it inside "
                    "`with static.program_guard(program):` before fetching")
            return []                     # e.g. startup program
        import jax as _jax

        fetch_ids = []
        for fv in fetch_list:
            if isinstance(fv, Tensor):
                fetch_ids.append(id(fv))
            else:
                raise TypeError(
                    "fetch_list entries must be Tensors built inside "
                    "program_guard (names are not tracked)")
        feed_arrs = {}
        for name, v in feed.items():
            ph = program._placeholders.get(name)
            if ph is None:
                raise KeyError(
                    f"feed {name!r} is not a static.data placeholder of "
                    f"this program (have: {list(program._placeholders)})")
            feed_arrs[id(ph)] = jax.numpy.asarray(
                v._data if isinstance(v, Tensor) else np.asarray(v))
        missing = [n for n, t in program._placeholders.items()
                   if id(t) not in feed_arrs]
        if missing:
            raise KeyError(
                f"placeholders {missing} were not fed (an unfed "
                "placeholder would silently replay its build-time zeros)")

        import jax.numpy as jnp
        params = {pid: t for pid, t in program._params.items()
                  if jnp.issubdtype(t._data.dtype, jnp.floating)}
        # ALL leaves (params, buffers, captured tensors) enter the jitted
        # replay as arguments, re-read each run — never baked as
        # trace-time constants (running stats would otherwise freeze).
        # Buffer WRITES recorded via core.tensor.record_mutation replay as
        # alias events; their final values are fetched with the outputs and
        # written back to the live Tensors below, so BN/IN running stats
        # train under Executor.run (reference: executor.cc:170).
        leaf_arrs = {lid: program._tensors[lid]._data
                     for lid in program.leaf_ids()}
        mutated = [mid for mid in program._mutated]
        param_arrs = {pid: leaf_arrs.pop(pid)
                      for pid in list(params)
                      if pid in leaf_arrs}
        train = program._optimizer is not None and program._loss is not None

        sig = (id(program), len(program._ops), tuple(sorted(feed_arrs)),
               tuple((a.shape, str(a.dtype)) for _, a in
                     sorted(feed_arrs.items())), tuple(fetch_ids), train)
        fns = program._run_cache.get(sig)
        if fns is None:
            def forward(feed_d, param_d, leaf_d):
                env = dict(feed_d)
                env.update(leaf_d)
                env.update(param_d)
                env = program._replay(env)
                return ([env[fid] for fid in fetch_ids],
                        {mid: env[mid] for mid in mutated})

            fwd_jit = _jax.jit(forward)
            grad_jit = None
            if train:
                loss_id = id(program._loss)

                def loss_fn(param_d, feed_d, leaf_d):
                    env = dict(feed_d)
                    env.update(leaf_d)
                    env.update(param_d)
                    env = program._replay(env)
                    fetched = [env[fid] for fid in fetch_ids]
                    muts = {mid: env[mid] for mid in mutated}
                    return (env[loss_id].astype(jax.numpy.float32),
                            (fetched, muts))

                # stat-update paths must not leak into the parameter
                # gradients — the EMA write is stop-gradient by nature
                grad_jit = _jax.jit(_jax.value_and_grad(loss_fn,
                                                        has_aux=True))
            fns = (fwd_jit, grad_jit)
            program._run_cache[sig] = fns
        fwd_jit, grad_jit = fns

        def write_back(muts):
            for mid, val in muts.items():
                program._tensors[mid]._data = val

        if train:
            (_, (fetched, muts)), grads = grad_jit(param_arrs, feed_arrs,
                                                   leaf_arrs)
            write_back(muts)
            # hand gradients to the optimizer's own fused update
            for pid, t in params.items():
                g = grads.get(pid)
                if g is not None and getattr(t, "trainable", True):
                    t.grad = Tensor(g)
            opt = program._optimizer
            if opt._parameter_list is None:
                # `SGD(lr).minimize(loss)` static pattern: adopt the
                # program's parameters
                opt._parameter_list = [t for t in params.values()
                                       if getattr(t, "trainable", True)]
            opt.step()
            program._optimizer.clear_grad()
            return fetched
        fetched, muts = fwd_jit(feed_arrs, param_arrs, leaf_arrs)
        write_back(muts)
        return fetched


# static-style layer helpers + functional control flow live in static.nn
# (imported at module top)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Persist a deployable model (reference: fluid/io.py:1246 /
    paddle.static.save_inference_model).

    TPU-native: the deploy artifact is the jit.save bundle (StableHLO +
    serialized executable + params). `fetch_vars` is the model — either a
    Layer or a callable over the feed tensors; `feed_vars` are InputSpecs
    (or Tensors whose shape/dtype define the signature)."""
    from ..jit.input_spec import InputSpec
    from ..jit.to_static import save as jsave
    from ..nn.layer import Layer

    specs = []
    for v in feed_vars:
        if isinstance(v, InputSpec):
            specs.append(v)
        else:
            arr = v._data if isinstance(v, Tensor) else np.asarray(v)
            specs.append(InputSpec(tuple(arr.shape), str(arr.dtype)))

    model = fetch_vars
    if isinstance(model, (list, tuple)):
        if len(model) != 1:
            raise ValueError("pass ONE Layer/callable as fetch_vars; "
                             "multi-output models return tuples")
        model = model[0]
    if not isinstance(model, Layer):
        if not callable(model):
            raise TypeError(
                "fetch_vars must be a Layer or a callable over the feed "
                "tensors (legacy Variable graphs do not exist here)")
        fn = model

        class _FnLayer(Layer):
            def forward(self, *xs):
                return fn(*xs)

        model = _FnLayer()
    jsave(model, path_prefix, input_spec=specs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Load a deployable model (reference: fluid/io.py:1466).

    Returns (program, feed_names, fetch_names) for surface parity, where
    `program` is a runnable TranslatedLayer: call
    `program(*inputs)` or `executor.run(program, feed=...)`."""
    from ..jit.to_static import load as jload

    translated = jload(path_prefix)
    if isinstance(translated, dict):
        raise ValueError(
            f"{path_prefix!r} holds weights only (saved without "
            "input_spec); load with paddle.jit.load for the params dict")
    spec = translated._meta.get("input_spec") or []
    feed_names = [f"x{i}" for i in range(len(spec))]
    return translated, feed_names, ["out0"]
