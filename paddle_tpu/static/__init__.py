"""paddle_tpu.static — static-graph compatibility facade.

The reference's static mode (ProgramDesc + Executor, reference:
python/paddle/fluid/framework.py Program:4392, executor.py:1065) maps onto
jit tracing here: a "Program" is a traced pure function; the "Executor" jit
compiles and runs it. This module offers the paddle.static surface for
users migrating static-graph code; new code should use paddle_tpu.jit.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core import dtypes
from ..core.tensor import Tensor
from ..jit.input_spec import InputSpec

from . import nn  # noqa: F401,E402  (functional control flow: cond/while_loop)

_static_mode = [False]


def _enable_static_mode():
    _static_mode[0] = True


def _in_static_mode():
    return _static_mode[0]


class Program:
    """A recorded pure function over named inputs (ProgramDesc analogue)."""

    def __init__(self):
        self._build_fn = None  # set by program_guard recording
        self._inputs: Dict[str, InputSpec] = {}
        self._fetch: List = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m = _default_main[0]
    prev_s = _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0] = prev_m
        _default_startup[0] = prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference: fluid/data.py). In eager-first mode
    this returns a zero placeholder Tensor tagged with its name."""
    shape = tuple(1 if (d is None or d < 0) else d for d in shape)
    t = Tensor(np.zeros(shape, np.dtype(dtypes.convert_dtype(dtype))))
    t.name = name
    return t


class CompiledProgram:
    """A jit-compiled pure function over named feeds (the working analogue
    of the reference's CompiledProgram, compiler.py). Built from a python
    callable; the Executor compiles once per feed signature and caches."""

    def __init__(self, fn):
        self.fn = fn
        self._cache = {}

    def _run(self, feed: Dict):
        names = tuple(sorted(feed))
        arrs = {k: (v._data if isinstance(v, Tensor)
                    else jax.numpy.asarray(v)) for k, v in feed.items()}
        sig = (names, tuple((tuple(a.shape), str(a.dtype))
                            for a in (arrs[n] for n in names)))
        jitted = self._cache.get(sig)
        if jitted is None:
            def pure(kw):
                out = self.fn(**{k: Tensor(v) for k, v in kw.items()})
                outs = out if isinstance(out, (list, tuple)) else [out]
                return [o._data if isinstance(o, Tensor) else o
                        for o in outs]
            jitted = jax.jit(pure)
            self._cache[sig] = jitted
        return jitted(arrs)


class Executor:
    """Executor over callables / CompiledProgram.

    The reference executes serialized ProgramDescs (executor.py:1065); the
    TPU-native 'program' is a traceable python callable — `run` jit
    compiles it (cached per feed signature) and fetches numpy results. For
    training loops prefer paddle_tpu.jit.TrainStep (donated buffers,
    optimizer fused into the step).
    """

    def __init__(self, place=None):
        self.place = place
        self._compiled: Dict[int, CompiledProgram] = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if isinstance(program, CompiledProgram):
            outs = program._run(feed or {})
        elif callable(program):
            # memoize per callable: repeated exe.run(fn, ...) hits the same
            # jit cache instead of retracing+recompiling every call
            cp = self._compiled.get(id(program))
            if cp is None or cp.fn is not program:
                cp = CompiledProgram(program)
                self._compiled[id(program)] = cp
            outs = cp._run(feed or {})
        else:
            raise TypeError(
                "paddle_tpu.static.Executor runs python callables or "
                "CompiledProgram (the TPU-native 'program'); legacy "
                "ProgramDesc graphs do not exist in this framework — build "
                "models eagerly and use jit.TrainStep for compiled "
                "training.")
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


# static-style layer helpers + functional control flow live in static.nn
# (imported at module top)
