"""paddle_tpu.static — static-graph compatibility facade.

The reference's static mode (ProgramDesc + Executor, reference:
python/paddle/fluid/framework.py Program:4392, executor.py:1065) maps onto
jit tracing here: a "Program" is a traced pure function; the "Executor" jit
compiles and runs it. This module offers the paddle.static surface for
users migrating static-graph code; new code should use paddle_tpu.jit.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core import dtypes
from ..core.tensor import Tensor
from ..jit.input_spec import InputSpec

from . import nn  # noqa: F401,E402  (functional control flow: cond/while_loop)

_static_mode = [False]


def _enable_static_mode():
    _static_mode[0] = True


def _in_static_mode():
    return _static_mode[0]


class Program:
    """A recorded pure function over named inputs (ProgramDesc analogue)."""

    def __init__(self):
        self._build_fn = None  # set by program_guard recording
        self._inputs: Dict[str, InputSpec] = {}
        self._fetch: List = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m = _default_main[0]
    prev_s = _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0] = prev_m
        _default_startup[0] = prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference: fluid/data.py). In eager-first mode
    this returns a zero placeholder Tensor tagged with its name."""
    shape = tuple(1 if (d is None or d < 0) else d for d in shape)
    t = Tensor(np.zeros(shape, np.dtype(dtypes.convert_dtype(dtype))))
    t.name = name
    return t


class CompiledProgram:
    """A jit-compiled pure function over named feeds (the working analogue
    of the reference's CompiledProgram, compiler.py). Built from a python
    callable; the Executor compiles once per feed signature and caches."""

    def __init__(self, fn):
        self.fn = fn
        self._cache = {}

    def _run(self, feed: Dict):
        names = tuple(sorted(feed))
        arrs = {k: (v._data if isinstance(v, Tensor)
                    else jax.numpy.asarray(v)) for k, v in feed.items()}
        sig = (names, tuple((tuple(a.shape), str(a.dtype))
                            for a in (arrs[n] for n in names)))
        jitted = self._cache.get(sig)
        if jitted is None:
            def pure(kw):
                out = self.fn(**{k: Tensor(v) for k, v in kw.items()})
                outs = out if isinstance(out, (list, tuple)) else [out]
                return [o._data if isinstance(o, Tensor) else o
                        for o in outs]
            jitted = jax.jit(pure)
            self._cache[sig] = jitted
        return jitted(arrs)


class Executor:
    """Executor over callables / CompiledProgram.

    The reference executes serialized ProgramDescs (executor.py:1065); the
    TPU-native 'program' is a traceable python callable — `run` jit
    compiles it (cached per feed signature) and fetches numpy results. For
    training loops prefer paddle_tpu.jit.TrainStep (donated buffers,
    optimizer fused into the step).
    """

    def __init__(self, place=None):
        self.place = place
        self._compiled: Dict[int, CompiledProgram] = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if isinstance(program, CompiledProgram):
            outs = program._run(feed or {})
        elif callable(program):
            # memoize per callable: repeated exe.run(fn, ...) hits the same
            # jit cache instead of retracing+recompiling every call
            cp = self._compiled.get(id(program))
            if cp is None or cp.fn is not program:
                cp = CompiledProgram(program)
                self._compiled[id(program)] = cp
            outs = cp._run(feed or {})
        else:
            raise TypeError(
                "paddle_tpu.static.Executor runs python callables or "
                "CompiledProgram (the TPU-native 'program'); legacy "
                "ProgramDesc graphs do not exist in this framework — build "
                "models eagerly and use jit.TrainStep for compiled "
                "training.")
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


# static-style layer helpers + functional control flow live in static.nn
# (imported at module top)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Persist a deployable model (reference: fluid/io.py:1246 /
    paddle.static.save_inference_model).

    TPU-native: the deploy artifact is the jit.save bundle (StableHLO +
    serialized executable + params). `fetch_vars` is the model — either a
    Layer or a callable over the feed tensors; `feed_vars` are InputSpecs
    (or Tensors whose shape/dtype define the signature)."""
    from ..jit.input_spec import InputSpec
    from ..jit.to_static import save as jsave
    from ..nn.layer import Layer

    specs = []
    for v in feed_vars:
        if isinstance(v, InputSpec):
            specs.append(v)
        else:
            arr = v._data if isinstance(v, Tensor) else np.asarray(v)
            specs.append(InputSpec(tuple(arr.shape), str(arr.dtype)))

    model = fetch_vars
    if isinstance(model, (list, tuple)):
        if len(model) != 1:
            raise ValueError("pass ONE Layer/callable as fetch_vars; "
                             "multi-output models return tuples")
        model = model[0]
    if not isinstance(model, Layer):
        if not callable(model):
            raise TypeError(
                "fetch_vars must be a Layer or a callable over the feed "
                "tensors (legacy Variable graphs do not exist here)")
        fn = model

        class _FnLayer(Layer):
            def forward(self, *xs):
                return fn(*xs)

        model = _FnLayer()
    jsave(model, path_prefix, input_spec=specs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Load a deployable model (reference: fluid/io.py:1466).

    Returns (program, feed_names, fetch_names) for surface parity, where
    `program` is a runnable TranslatedLayer: call
    `program(*inputs)` or `executor.run(program, feed=...)`."""
    from ..jit.to_static import load as jload

    translated = jload(path_prefix)
    if isinstance(translated, dict):
        raise ValueError(
            f"{path_prefix!r} holds weights only (saved without "
            "input_spec); load with paddle.jit.load for the params dict")
    spec = translated._meta.get("input_spec") or []
    feed_names = [f"x{i}" for i in range(len(spec))]
    return translated, feed_names, ["out0"]
