"""Functional control flow over traced tensors.

reference parity: paddle/fluid/layers/control_flow.py cond(:2323),
while_loop(:1045), case/switch_case — backed by
operators/controlflow/conditional_block_op.cc and while_op.cc (sub-block
programs executed by the interpreter).

TPU-native design: data-dependent control flow must stay INSIDE the
compiled program (a host round-trip per branch would stall the TPU), so
these map 1:1 onto XLA's native control ops — ``lax.cond`` /
``lax.while_loop`` / ``lax.switch``. Both branches are compiled; the
predicate selects on device. Python ``if tensor:`` raises a guided error
instead (see jit.to_static) because tracing cannot see concrete values.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit.functional import unwrap, wrap

__all__ = ["cond", "while_loop", "case", "switch_case", "fc"]


def fc(x, size, num_flatten_dims=1, activation=None, name=None):
    """Static-style fully-connected helper (reference: fluid/layers/nn.py
    fc): flattens trailing dims, creates a fresh Linear, optional
    activation by name."""
    import numpy as np

    from ..nn import Linear
    from ..nn import functional as F
    from ..tensor.manipulation import reshape
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_dim, size)
    flat = reshape(x, tuple(x.shape[:num_flatten_dims]) + (in_dim,))
    out = layer(flat)
    if activation:
        out = getattr(F, activation)(out)
    return out


def _as_scalar_pred(pred):
    p = pred._data if isinstance(pred, Tensor) else jnp.asarray(pred)
    if p.ndim:
        p = p.reshape(())
    return p.astype(bool)


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """Run true_fn() or false_fn() selected by a traced boolean scalar.

    reference: control_flow.py:2323 cond (conditional_block sub-programs).
    Both branches are traced/compiled; XLA executes the selected one on
    device. Branch outputs must match in structure/shape/dtype.
    Extra ``operands`` are passed to both branches (closure capture also
    works, as in the reference).
    """
    raw = [o._data if isinstance(o, Tensor) else o for o in operands]

    def tb(ops):
        return unwrap(true_fn(*wrap(list(ops))))

    def fb(ops):
        return unwrap(false_fn(*wrap(list(ops))))

    out = jax.lax.cond(_as_scalar_pred(pred), tb, fb, tuple(raw))
    return wrap(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars):
    """reference: control_flow.py:1045 while_loop (while_op sub-program).
    Maps to lax.while_loop: carried values must keep shape/dtype; the
    condition returns a scalar bool tensor."""
    is_seq = isinstance(loop_vars, (list, tuple))
    seq: Sequence = loop_vars if is_seq else [loop_vars]
    raw = tuple(v._data if isinstance(v, Tensor) else jnp.asarray(v)
                for v in seq)

    def c(vals):
        out = cond_fn(*wrap(list(vals)))
        return _as_scalar_pred(out)

    def b(vals):
        out = body_fn(*wrap(list(vals)))
        out_seq = out if isinstance(out, (list, tuple)) else [out]
        if len(out_seq) != len(vals):
            raise ValueError(
                f"while_loop body returned {len(out_seq)} values, "
                f"expected {len(vals)} (loop_vars structure must be "
                "invariant)")
        return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in out_seq)

    out = jax.lax.while_loop(c, b, raw)
    wrapped = [wrap(o) for o in out]
    return wrapped if is_seq else wrapped[0]


def case(pred_fn_pairs: Sequence[Tuple], default: Callable = None):
    """First-match-wins branch list (reference: control_flow.py case).
    Lowered as a chain of lax.cond."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")

    def build(pairs):
        (pred, fn), rest = pairs[0], pairs[1:]
        if rest:
            return cond(pred, fn, lambda: build(rest))
        if default is not None:
            return cond(pred, fn, default)
        return fn()

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default: Callable = None):
    """Integer-indexed branch select (reference: control_flow.py
    switch_case) -> lax.switch."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        if keys != list(range(len(keys))):
            raise ValueError(
                "switch_case branch_fns keys must be 0..N-1 for the "
                "dense lax.switch lowering; pad missing indices with "
                "the default fn")
        fns: List[Callable] = [branch_fns[k] for k in keys]
    else:
        fns = list(branch_fns)
    if default is not None:
        fns = fns + [default]
    idx = branch_index._data if isinstance(branch_index, Tensor) \
        else jnp.asarray(branch_index)
    idx = idx.reshape(()).astype(jnp.int32)
    if default is not None:
        idx = jnp.where((idx < 0) | (idx >= len(fns) - 1),
                        len(fns) - 1, idx)
    out = jax.lax.switch(idx, [lambda f=f: unwrap(f()) for f in fns])
    return wrap(out)
