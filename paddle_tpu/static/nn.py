"""Functional control flow over traced tensors.

reference parity: paddle/fluid/layers/control_flow.py cond(:2323),
while_loop(:1045), case/switch_case — backed by
operators/controlflow/conditional_block_op.cc and while_op.cc (sub-block
programs executed by the interpreter).

TPU-native design: data-dependent control flow must stay INSIDE the
compiled program (a host round-trip per branch would stall the TPU), so
these map 1:1 onto XLA's native control ops — ``lax.cond`` /
``lax.while_loop`` / ``lax.switch``. Both branches are compiled; the
predicate selects on device. Python ``if tensor:`` raises a guided error
instead (see jit.to_static) because tracing cannot see concrete values.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit.functional import unwrap, wrap

__all__ = ["cond", "while_loop", "case", "switch_case", "fc"]


def fc(x, size, num_flatten_dims=1, activation=None, name=None):
    """Static-style fully-connected helper (reference: fluid/layers/nn.py
    fc): flattens trailing dims, creates a fresh Linear, optional
    activation by name."""
    import numpy as np

    from ..nn import Linear
    from ..nn import functional as F
    from ..tensor.manipulation import reshape
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_dim, size)
    flat = reshape(x, tuple(x.shape[:num_flatten_dims]) + (in_dim,))
    out = layer(flat)
    if activation:
        out = getattr(F, activation)(out)
    return out


def _as_scalar_pred(pred):
    p = pred._data if isinstance(pred, Tensor) else jnp.asarray(pred)
    if p.ndim:
        p = p.reshape(())
    return p.astype(bool)


def _active_recorder():
    from ..core.tensor import _static_recorders
    return _static_recorders[-1] if _static_recorders else None


def _subtrace(fn, arg_tensors):
    """Trace ``fn(*arg_tensors)`` into a fresh sub-Program (the analogue of
    the reference's sub-block build for conditional_block_op.cc:1 /
    while_op.cc:1). Returns (sub_program, out_tensors, captured_leaf_ids):
    leaf ids are tensors the branch READS from the enclosing scope
    (parameters, intermediates) — they become explicit inputs of the
    combined op so replay never bakes them as trace constants."""
    from . import Program
    from ..core.tensor import pop_static_recorder, push_static_recorder
    sub = Program()
    push_static_recorder(sub)
    try:
        out = fn(*arg_tensors)
    finally:
        pop_static_recorder()
    if sub._mutated:
        raise NotImplementedError(
            "in-place buffer writes (BN running stats, QAT scales) inside "
            "a recorded cond/while branch are not supported: the write "
            "would be conditional on a traced predicate. Hoist the "
            "stateful layer out of the branch, or run it in eval mode.")
    was_seq = isinstance(out, (list, tuple))
    outs = out if was_seq else [out]
    arg_ids = {id(t) for t in arg_tensors}
    leaves = [lid for lid in sub.leaf_ids() if lid not in arg_ids]
    return sub, list(outs), leaves, was_seq


def _merge_leaves(subs_and_leaves):
    """Ordered union of captured-leaf ids across sub-programs; returns
    (leaf_ids, leaf_tensors)."""
    leaf_ids = list(dict.fromkeys(
        lid for _, leaves in subs_and_leaves for lid in leaves))
    tensors = []
    for lid in leaf_ids:
        for sub, _ in subs_and_leaves:
            t = sub._tensors.get(lid)
            if t is not None:
                tensors.append(t)
                break
    return leaf_ids, tensors


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """Run true_fn() or false_fn() selected by a traced boolean scalar.

    reference: control_flow.py:2323 cond (conditional_block sub-programs).
    Both branches are traced/compiled; XLA executes the selected one on
    device. Branch outputs must match in structure/shape/dtype.
    Extra ``operands`` are passed to both branches (closure capture also
    works, as in the reference).

    Under an active ``static.program_guard`` the cond records as ONE op
    whose fn replays both branch sub-programs inside ``lax.cond`` — the
    TPU-native analogue of the reference's conditional_block sub-block ops
    (conditional_block_op.cc:1): a recorded Program containing a branch
    replays under Executor.run, including gradient flow to captured
    parameters (lax.cond is reverse-differentiable)."""
    rec = _active_recorder()
    if rec is not None:
        return _recorded_cond(pred, true_fn, false_fn, operands)
    raw = [o._data if isinstance(o, Tensor) else o for o in operands]

    def tb(ops):
        return unwrap(true_fn(*wrap(list(ops))))

    def fb(ops):
        return unwrap(false_fn(*wrap(list(ops))))

    out = jax.lax.cond(_as_scalar_pred(pred), tb, fb, tuple(raw))
    return wrap(out)


def _recorded_cond(pred, true_fn, false_fn, operands):
    from ..core.tensor import Tensor as _T, apply
    ops = [o if isinstance(o, _T) else _T(jnp.asarray(o))
           for o in operands]
    sub_t, outs_t, leaves_t, seq_t = _subtrace(
        lambda *a: true_fn(*a) if a else true_fn(), ops)
    sub_f, outs_f, leaves_f, seq_f = _subtrace(
        lambda *a: false_fn(*a) if a else false_fn(), ops)
    if len(outs_t) != len(outs_f):
        raise TypeError(
            f"cond branches must return the same structure: true_fn gave "
            f"{len(outs_t)} value(s), false_fn {len(outs_f)}")
    leaf_ids, leaf_tensors = _merge_leaves(
        [(sub_t, leaves_t), (sub_f, leaves_f)])
    n_ops = len(ops)
    op_ids = [id(t) for t in ops]

    def branch(sub, out_tensors):
        out_ids = [id(o) for o in out_tensors]

        def run(arg):
            op_vals, leaf_vals = arg
            env = dict(zip(op_ids, op_vals))
            env.update(zip(leaf_ids, leaf_vals))
            env = sub._replay(env)
            # an output can be a passthrough (env) or a branch-local
            # constant (its recorded array)
            return tuple(env.get(i, t._data)
                         for i, t in zip(out_ids, out_tensors))
        return run

    single = not seq_t and len(outs_t) == 1

    def combined(pred_raw, *vals):
        op_vals = tuple(vals[:n_ops])
        leaf_vals = tuple(vals[n_ops:])
        res = jax.lax.cond(
            _as_scalar_pred(pred_raw), branch(sub_t, outs_t),
            branch(sub_f, outs_f), (op_vals, leaf_vals))
        return res[0] if single else tuple(res)

    res = apply(combined, pred, *ops, *leaf_tensors, name="static_cond")
    return res


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars):
    """reference: control_flow.py:1045 while_loop (while_op sub-program).
    Maps to lax.while_loop: carried values must keep shape/dtype; the
    condition returns a scalar bool tensor.

    Under an active ``static.program_guard`` the loop records as ONE op
    replaying the cond/body sub-programs inside ``lax.while_loop``
    (reference: while_op.cc:1 runs the sub-block per iteration). Note
    ``lax.while_loop`` is not reverse-differentiable: a recorded Program
    may contain a while for inference/decode replay, but the loss of a
    training Program must not depend on one (the reference's while_grad
    has no XLA analogue; use a bounded `for`+`lax.scan` style loop via
    dy2static for differentiable loops)."""
    is_seq = isinstance(loop_vars, (list, tuple))
    seq: Sequence = loop_vars if is_seq else [loop_vars]
    rec = _active_recorder()
    if rec is not None:
        return _recorded_while(cond_fn, body_fn, seq, is_seq)
    raw = tuple(v._data if isinstance(v, Tensor) else jnp.asarray(v)
                for v in seq)

    def c(vals):
        out = cond_fn(*wrap(list(vals)))
        return _as_scalar_pred(out)

    def b(vals):
        out = body_fn(*wrap(list(vals)))
        out_seq = out if isinstance(out, (list, tuple)) else [out]
        if len(out_seq) != len(vals):
            raise ValueError(
                f"while_loop body returned {len(out_seq)} values, "
                f"expected {len(vals)} (loop_vars structure must be "
                "invariant)")
        return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in out_seq)

    out = jax.lax.while_loop(c, b, raw)
    wrapped = [wrap(o) for o in out]
    return wrapped if is_seq else wrapped[0]


def _recorded_while(cond_fn, body_fn, seq, is_seq):
    from ..core.tensor import Tensor as _T, apply, no_grad
    vars_t = [v if isinstance(v, _T) else _T(jnp.asarray(v)) for v in seq]
    sub_c, outs_c, leaves_c, _ = _subtrace(cond_fn, vars_t)
    sub_b, outs_b, leaves_b, _ = _subtrace(body_fn, vars_t)
    if len(outs_b) != len(vars_t):
        raise ValueError(
            f"while_loop body returned {len(outs_b)} values, expected "
            f"{len(vars_t)} (loop_vars structure must be invariant)")
    leaf_ids, leaf_tensors = _merge_leaves(
        [(sub_c, leaves_c), (sub_b, leaves_b)])
    n = len(vars_t)
    var_ids = [id(v) for v in vars_t]
    pred_t = outs_c[0]
    body_out_ids = [id(o) for o in outs_b]

    def combined(*vals):
        carry0 = tuple(vals[:n])
        leaf_vals = tuple(vals[n:])

        def c(carry):
            env = dict(zip(var_ids, carry))
            env.update(zip(leaf_ids, leaf_vals))
            env = sub_c._replay(env)
            return _as_scalar_pred(env.get(id(pred_t), pred_t._data))

        def b(carry):
            env = dict(zip(var_ids, carry))
            env.update(zip(leaf_ids, leaf_vals))
            env = sub_b._replay(env)
            return tuple(env.get(i, t._data)
                         for i, t in zip(body_out_ids, outs_b))

        return tuple(jax.lax.while_loop(c, b, carry0))

    # lax.while_loop has no reverse-mode rule — keep the eager apply off
    # the tape (matching the unrecorded path, whose outputs are detached)
    with no_grad():
        res = apply(combined, *vars_t, *leaf_tensors, name="static_while")
    out = list(res) if isinstance(res, (tuple, list)) else [res]
    return out if is_seq else out[0]


def case(pred_fn_pairs: Sequence[Tuple], default: Callable = None):
    """First-match-wins branch list (reference: control_flow.py case).
    Lowered as a chain of lax.cond."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")

    def build(pairs):
        (pred, fn), rest = pairs[0], pairs[1:]
        if rest:
            return cond(pred, fn, lambda: build(rest))
        if default is not None:
            return cond(pred, fn, default)
        return fn()

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default: Callable = None):
    """Integer-indexed branch select (reference: control_flow.py
    switch_case) -> lax.switch."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        if keys != list(range(len(keys))):
            raise ValueError(
                "switch_case branch_fns keys must be 0..N-1 for the "
                "dense lax.switch lowering; pad missing indices with "
                "the default fn")
        fns: List[Callable] = [branch_fns[k] for k in keys]
    else:
        fns = list(branch_fns)
    if default is not None:
        fns = fns + [default]
    idx = branch_index._data if isinstance(branch_index, Tensor) \
        else jnp.asarray(branch_index)
    idx = idx.reshape(()).astype(jnp.int32)
    if default is not None:
        idx = jnp.where((idx < 0) | (idx >= len(fns) - 1),
                        len(fns) - 1, idx)
    out = jax.lax.switch(idx, [lambda f=f: unwrap(f()) for f in fns])
    return wrap(out)
