"""Model summary (reference: python/paddle/hapi/model_summary.py)."""

from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None):
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for _, p in layer._parameters.items():
            if p is None:
                continue
            n = int(np.prod(p.shape)) if p.shape else 1
            n_params += n
        if n_params or not layer._sub_layers:
            rows.append((name or type(net).__name__, type(layer).__name__, n_params))
    for _, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if getattr(p, "trainable", True):
            trainable_params += n
    lines = [f"{'Layer':<46}{'Type':<26}{'Params':>12}", "-" * 84]
    for name, tname, n in rows:
        lines.append(f"{name:<46}{tname:<26}{n:>12,}")
    lines += ["-" * 84,
              f"Total params: {total_params:,}",
              f"Trainable params: {trainable_params:,}"]
    out = "\n".join(lines)
    print(out)
    return {"total_params": total_params, "trainable_params": trainable_params}
