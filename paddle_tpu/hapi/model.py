"""High-level Model API (reference: python/paddle/hapi/model.py —
Model:906, fit:1556, DynamicGraphAdapter:666, StaticGraphAdapter:247).

TPU-native: in dynamic mode `prepare()` builds a jitted TrainStep
(forward+loss+grad+opt in one compiled program with donation); under
``paddle.enable_static()`` it builds a RECORDED static.Program driven by
``Executor.run`` — the working analogue of the reference's
StaticGraphAdapter (hapi/model.py:247: prepare builds feed/fetch
programs, fit runs them on the executor). With ``fleet.init`` active the
dynamic path becomes fleet-distributed: the train step is laid out over
the hybrid mesh with the batch sharded on the dp axis
(reference: hapi/model.py:666 DynamicGraphAdapter wrapping the network
in fleet.distributed_model).
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, no_grad
from ..io.dataloader import DataLoader
from ..jit.to_static import TrainStep
from ..metric import Metric
from . import callbacks as cbks_mod


class StaticGraphAdapter:
    """Build + run recorded Programs for a hapi Model (reference:
    hapi/model.py:247 — _make_program builds the train program from the
    Model's InputSpecs, clone(for_test=True) derives the eval program,
    run() feeds/fetches through the Executor)."""

    def __init__(self, model: "Model"):
        from .. import static
        from ..jit.input_spec import InputSpec

        if not model._inputs:
            raise ValueError(
                "static-mode Model needs `inputs=[InputSpec(...)]` at "
                "construction (the recorded Program's placeholders come "
                "from them — reference hapi/model.py:906 makes the same "
                "demand of its static adapter)")

        def specs(raw, prefix):
            out = []
            for i, s in enumerate(raw or []):
                if not isinstance(s, InputSpec):
                    s = InputSpec(s.shape, getattr(s, "dtype", "float32"))
                out.append((s.name or f"{prefix}{i}", s))
            return out

        self._in_specs = specs(model._inputs, "x")
        self._lab_specs = specs(model._labels, "label")
        self.model = model
        self._exe = static.Executor()

        self.train_prog = static.Program()
        startup = static.Program()
        model.network.train()
        with static.program_guard(self.train_prog, startup):
            ins = [static.data(n, list(s.shape), s.dtype)
                   for n, s in self._in_specs]
            labs = [static.data(n, list(s.shape), s.dtype)
                    for n, s in self._lab_specs]
            outs = model.network(*ins)
            self._outputs = list(outs) if isinstance(outs, (list, tuple)) \
                else [outs]
            self._loss_var = None
            if model._loss is not None and labs:
                self._loss_var = model._loss(self._outputs[0], labs[0])
                if model._optimizer is not None:
                    model._optimizer.minimize(self._loss_var)
        # eval twin: train-only ops (dropout, BN batch stats) swapped for
        # their recorded eval variants, writes stripped, optimizer dropped
        self.test_prog = self.train_prog.clone(for_test=True)

    def _feed(self, xs, labels=None):
        feed = {}
        batch = None
        for (name, spec), v in zip(self._in_specs, xs):
            arr = np.asarray(v._data if isinstance(v, Tensor) else v)
            feed[name] = arr
            batch = arr.shape[0] if arr.ndim else None
        labels = labels or []
        for i, (name, spec) in enumerate(self._lab_specs):
            if i < len(labels):
                v = labels[i]
                feed[name] = np.asarray(
                    v._data if isinstance(v, Tensor) else v)
            else:
                # predict path: label placeholders must still be fed (the
                # Executor refuses silent build-time zeros); the fetch set
                # doesn't read them, XLA dead-code-eliminates the loss
                shape = tuple(batch if (d is None or int(d) < 1) else int(d)
                              for d in spec.shape) or ()
                feed[name] = np.zeros(shape, spec.dtype)
        return feed

    def train_batch(self, xs, labels=None):
        (lv,) = self._exe.run(self.train_prog,
                              feed=self._feed(xs, labels),
                              fetch_list=[self._loss_var])
        return [float(lv)]

    def eval_batch(self, xs, labels=None):
        # without labels the loss would be computed against the zero-fill
        # placeholder feed — return no loss, as the dynamic path does
        want_loss = self._loss_var is not None and bool(labels)
        fetch = ([self._loss_var] if want_loss else []) + self._outputs
        res = self._exe.run(self.test_prog, feed=self._feed(xs, labels),
                            fetch_list=fetch)
        metrics = []
        if want_loss:
            metrics.append(float(res[0]))
            res = res[1:]
        if labels:
            for m in self.model._metrics:
                corr = m.compute(Tensor(res[0]), labels[0]
                                 if isinstance(labels[0], Tensor)
                                 else Tensor(np.asarray(labels[0])))
                m.update(corr)
        return metrics

    def predict_batch(self, xs):
        res = self._exe.run(self.test_prog, feed=self._feed(xs),
                            fetch_list=self._outputs)
        return [np.asarray(r) for r in res]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs if inputs is None or isinstance(
            inputs, (list, tuple)) else [inputs]
        self._labels = labels if labels is None or isinstance(
            labels, (list, tuple)) else [labels]
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._adapter: Optional[StaticGraphAdapter] = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        else:
            self._metrics = []

        import paddle_tpu as paddle
        if not paddle.in_dynamic_mode():
            # static mode: recorded Program + Executor (the reference's
            # StaticGraphAdapter path, hapi/model.py:247)
            self._adapter = StaticGraphAdapter(self)
            return self

        if optimizer is not None and loss is not None:
            loss_layer = loss

            def loss_fn(net, *batch):
                # convention: last element(s) are labels; single-label case
                *xs, y = batch
                out = net(*xs)
                return loss_layer(out, y)

            # fleet-distributed fit (reference: hapi/model.py:666 wraps
            # the network AND optimizer per parallel mode): with an active
            # hybrid mesh the train step is SPMD over it, batch sharded on
            # dp; the optimizer goes through fleet.distributed_optimizer
            # so the active strategy (gradient_merge, localsgd) applies
            from ..distributed import fleet
            if fleet.init_is_called():
                from jax.sharding import PartitionSpec as P
                hcg = fleet.get_hybrid_communicate_group()
                if not hasattr(optimizer, "_fleet_strategy"):
                    optimizer = fleet.distributed_optimizer(optimizer)
                self._train_step = TrainStep(
                    self.network, loss_fn, optimizer, mesh=hcg.mesh,
                    data_spec=P("dp"))
            else:
                self._train_step = TrainStep(self.network, loss_fn,
                                             optimizer)
        return self

    # ------------------------------------------------------------------
    def forward(self, *inputs):
        """Delegate to the wrapped network (reference: Model.forward)."""
        return self.network(*inputs)

    @property
    def mode(self):
        return "train" if self.network.training else "eval"

    @mode.setter
    def mode(self, value):
        self.network.train() if value == "train" else self.network.eval()

    def train_batch(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        if self._adapter is not None:
            return self._adapter.train_batch(list(inputs),
                                             list(labels) if labels else [])
        batch = list(inputs) + (list(labels) if labels else [])
        self.network.train()
        loss = self._train_step(*batch)
        return [float(np.asarray(loss.data))]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._adapter is not None:
            labels_l = labels if labels is None or isinstance(
                labels, (list, tuple)) else [labels]
            return self._adapter.eval_batch(list(inputs),
                                            list(labels_l) if labels_l
                                            else [])
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_layer()
        outputs = self.network(*inputs)
        metrics = []
        if labels is not None and self._loss is not None:
            labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
            loss = self._loss(outputs, labels_l[0])
            metrics.append(float(np.asarray(loss.data)))
        for m in self._metrics:
            if labels is not None:
                labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
                corr = m.compute(outputs, labels_l[0])
                m.update(corr)
        return metrics

    @no_grad()
    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._adapter is not None:
            return self._adapter.predict_batch(list(inputs))
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_layer()
        out = self.network(*inputs)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o.data) for o in out]
        return [np.asarray(out.data)]

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        if not isinstance(train_data, DataLoader):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else \
                DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)

        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, verbose=verbose,
            steps=len(train_loader) if hasattr(train_loader, "__len__") else None,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            metrics=["loss"] + [n for m in self._metrics
                                for n in (m.name() if isinstance(m.name(), list)
                                          else [m.name()])])

        cbks.on_begin("train")
        # on_end runs even when training dies mid-epoch (KeyboardInterrupt,
        # OOM, a NaN-watchdog NonFiniteError): callbacks that acquire
        # process state in on_begin — MonitorCallback's FLAGS_monitor
        # flip, open files — must get their teardown
        try:
            steps_done = 0
            for epoch in range(epochs):
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                for step, batch in enumerate(train_loader):
                    cbks.on_batch_begin("train", step, {})
                    batch = batch if isinstance(batch, (tuple, list)) else [batch]
                    *xs, y = batch
                    losses = self.train_batch(xs, [y])
                    logs = {"loss": losses[0], "step": step}
                    cbks.on_batch_end("train", step, logs)
                    steps_done += 1
                    if num_iters is not None and steps_done >= num_iters:
                        break
                cbks.on_epoch_end(epoch, logs if "logs" in dir() else {})
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_loader, verbose=0)
                if self.stop_training or (num_iters is not None and steps_done >= num_iters):
                    break
        finally:
            cbks.on_end("train")
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            batch = batch if isinstance(batch, (tuple, list)) else [batch]
            *xs, y = batch
            out = self.eval_batch(xs, [y])
            if out:
                losses.append(out[0])
        result = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for n, v in zip(names, vals):
                result[n] = v
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for batch in loader:
            batch = batch if isinstance(batch, (tuple, list)) else [batch]
            xs = batch[:-1] if len(batch) > 1 else batch
            outputs.append(self.predict_batch(list(xs)))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as _save
        if self._train_step is not None:
            self._train_step.sync_to_layer()
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))
        if self._train_step is not None:
            # re-seed the compiled step's device state from the layer
            self._train_step.__init__(self.network, self._train_step.loss_fn,
                                      self._optimizer)

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
