"""High-level Model API (reference: python/paddle/hapi/model.py —
Model:906, fit:1556, DynamicGraphAdapter:666).

TPU-native: `prepare()` builds a jitted TrainStep (forward+loss+grad+opt in
one compiled program with donation) — the analogue of the reference's
static-graph adapter, without a Program in sight. `fit` drives DataLoaders
and callbacks around it.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, no_grad
from ..io.dataloader import DataLoader
from ..jit.to_static import TrainStep
from ..metric import Metric
from . import callbacks as cbks_mod


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        else:
            self._metrics = []

        if optimizer is not None and loss is not None:
            loss_layer = loss

            def loss_fn(net, *batch):
                # convention: last element(s) are labels; single-label case
                *xs, y = batch
                out = net(*xs)
                return loss_layer(out, y)

            self._train_step = TrainStep(self.network, loss_fn, optimizer)
        return self

    # ------------------------------------------------------------------
    def forward(self, *inputs):
        """Delegate to the wrapped network (reference: Model.forward)."""
        return self.network(*inputs)

    @property
    def mode(self):
        return "train" if self.network.training else "eval"

    @mode.setter
    def mode(self, value):
        self.network.train() if value == "train" else self.network.eval()

    def train_batch(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        batch = list(inputs) + (list(labels) if labels else [])
        self.network.train()
        loss = self._train_step(*batch)
        return [float(np.asarray(loss.data))]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_layer()
        outputs = self.network(*inputs)
        metrics = []
        if labels is not None and self._loss is not None:
            labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
            loss = self._loss(outputs, labels_l[0])
            metrics.append(float(np.asarray(loss.data)))
        for m in self._metrics:
            if labels is not None:
                labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
                corr = m.compute(outputs, labels_l[0])
                m.update(corr)
        return metrics

    @no_grad()
    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_layer()
        out = self.network(*inputs)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o.data) for o in out]
        return [np.asarray(out.data)]

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        if not isinstance(train_data, DataLoader):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else \
                DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)

        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, verbose=verbose,
            steps=len(train_loader) if hasattr(train_loader, "__len__") else None,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            metrics=["loss"] + [n for m in self._metrics
                                for n in (m.name() if isinstance(m.name(), list)
                                          else [m.name()])])

        cbks.on_begin("train")
        steps_done = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(train_loader):
                cbks.on_batch_begin("train", step, {})
                batch = batch if isinstance(batch, (tuple, list)) else [batch]
                *xs, y = batch
                losses = self.train_batch(xs, [y])
                logs = {"loss": losses[0], "step": step}
                cbks.on_batch_end("train", step, logs)
                steps_done += 1
                if num_iters is not None and steps_done >= num_iters:
                    break
            cbks.on_epoch_end(epoch, logs if "logs" in dir() else {})
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, verbose=0)
            if self.stop_training or (num_iters is not None and steps_done >= num_iters):
                break
        cbks.on_end("train")
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            batch = batch if isinstance(batch, (tuple, list)) else [batch]
            *xs, y = batch
            out = self.eval_batch(xs, [y])
            if out:
                losses.append(out[0])
        result = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for n, v in zip(names, vals):
                result[n] = v
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for batch in loader:
            batch = batch if isinstance(batch, (tuple, list)) else [batch]
            xs = batch[:-1] if len(batch) > 1 else batch
            outputs.append(self.predict_batch(list(xs)))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as _save
        if self._train_step is not None:
            self._train_step.sync_to_layer()
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))
        if self._train_step is not None:
            # re-seed the compiled step's device state from the layer
            self._train_step.__init__(self.network, self._train_step.loss_fn,
                                      self._optimizer)

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
