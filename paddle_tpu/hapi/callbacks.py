"""Callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "MonitorCallback", "config_callbacks",
           "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda s, l=None: None)(step, logs)

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call("on_batch_end", mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        self.seen = 0

    def on_train_batch_end(self, step, logs=None):
        self.seen += 1
        if self.verbose and step % self.log_freq == 0:
            loss = logs.get("loss") if logs else None
            msg = f"Epoch {self.epoch} step {step}"
            if loss is not None:
                msg += f": loss={loss:.4f}"
            print(msg)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch} done in {time.time() - self.t0:.1f}s")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None) if opt else None
        from ..optimizer.lr import LRScheduler as Sched
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            value = value[0]
        improved = (self.best is None or
                    (self.mode == "min" and value < self.best - self.min_delta) or
                    (self.mode == "max" and value > self.best + self.min_delta))
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


class MonitorCallback(Callback):
    """Stream the monitor metrics registry to an append-only JSONL file
    (paddle_tpu.monitor; render with ``tools/monitor_report.py``).

    Every epoch end appends the full registry snapshot tagged with the
    epoch number (plus a final ``event="train_end"`` snapshot), so the
    file is a per-epoch time series of counters — recompiles, comms
    bytes, step-time histograms — for the whole fit() run. The registry
    is resolved at dump time, so ``monitor.scoped_registry`` blocks and
    late ``FLAGS_monitor`` flips are honored.
    """

    def __init__(self, path, registry=None, set_monitor_flag=True):
        super().__init__()
        self.path = path
        self._registry = registry
        self._set_flag = set_monitor_flag
        self._flag_scope = None

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..monitor import get_registry
        return get_registry()

    def _dump(self, extra):
        try:
            self._reg().dump_jsonl(self.path, extra=extra)
        except OSError as e:          # telemetry must never kill training
            print(f"MonitorCallback: dump to {self.path} failed: {e}")

    def on_train_begin(self, logs=None):
        if self._set_flag and self._flag_scope is None:
            # flag_scope is the restore-capable override (keeps the
            # explicitly-set bit); held open across the fit() run —
            # Model.fit guarantees on_end("train") via its finally
            from ..core.flags import flag_scope
            self._flag_scope = flag_scope("monitor", True)
            self._flag_scope.__enter__()

    def on_epoch_end(self, epoch, logs=None):
        self._dump({"epoch": epoch})

    def on_train_end(self, logs=None):
        self._dump({"event": "train_end"})
        if self._flag_scope is not None:
            self._flag_scope.__exit__(None, None, None)
            self._flag_scope = None


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or []})
    return cl
