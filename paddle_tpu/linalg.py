"""paddle.linalg namespace (reference parity: python/paddle/linalg.py —
re-exports of tensor.linalg). All ops are tape-aware jnp.linalg wraps."""

from .tensor.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, inv, lstsq, lu, matrix_power, matrix_rank, multi_dot, norm,
    pinv, qr, slogdet, solve, svd, triangular_solve)

__all__ = ["cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det",
           "eig", "eigh", "eigvals", "eigvalsh", "inv", "lstsq", "lu",
           "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv", "qr",
           "slogdet", "solve", "svd", "triangular_solve"]
