"""Weight-decay regularizers (paddle.regularizer namespace).

reference parity: python/paddle/regularizer.py — L1Decay/L2Decay passed as
``weight_decay=`` to optimizers. The classes live with the optimizer (the
update rule folds the penalty gradient into the same jitted step:
L2 -> coeff * w, L1 -> coeff * sign(w), optimizer.py _coupled_decay);
this module is the public namespace alias.
"""

from .optimizer.optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]
