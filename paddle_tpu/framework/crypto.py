"""Model-file encryption.

reference parity: paddle/fluid/framework/io/crypto/cipher.h:24 —
CipherFactory/AesCipher let inference models ship encrypted
(paddle.fluid.io save/load with a cipher). The image has no OpenSSL
python bindings, so the cipher here is a keyed-BLAKE2b PRF in counter
mode with an encrypt-then-MAC tag — a dependency-free authenticated
stream cipher (CTR over a PRF is IND-CPA; the keyed-BLAKE2 MAC over
nonce+ciphertext gives integrity, which the reference's raw AES-CBC
never had: tampered files decrypt to garbage there, here they RAISE).

Format: MAGIC | nonce(16) | ciphertext | tag(32).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Optional

__all__ = ["Cipher", "CipherFactory", "encrypt_bytes", "decrypt_bytes",
           "encrypt_file", "decrypt_file", "generate_key"]

_MAGIC = b"PTPUENC1"
_NONCE = 16
_TAG = 32
_BLOCK = 64          # blake2b digest size = keystream block


class DecryptionError(ValueError):
    pass


def generate_key(nbytes: int = 32) -> bytes:
    """Random key (reference: CipherUtils::GenKey)."""
    return os.urandom(nbytes)


def _derive(key: bytes, label: bytes) -> bytes:
    return hashlib.blake2b(label, key=key, digest_size=32).digest()


def _keystream_xor(data: bytes, key: bytes, nonce: bytes) -> bytes:
    import numpy as np
    enc_key = _derive(key, b"enc")
    n_blocks = (len(data) + _BLOCK - 1) // _BLOCK
    # keystream assembled blockwise, XOR vectorized over the whole buffer
    # (a per-byte python loop runs single-digit MB/s — checkpoint-sized
    # payloads must stream at memory speed)
    ks = bytearray(n_blocks * _BLOCK)
    for blk in range(n_blocks):
        ctr = struct.pack("<Q", blk)
        ks[blk * _BLOCK:(blk + 1) * _BLOCK] = hashlib.blake2b(
            nonce + ctr, key=enc_key, digest_size=_BLOCK).digest()
    a = np.frombuffer(data, np.uint8)
    b = np.frombuffer(bytes(ks[:len(data)]), np.uint8)
    return np.bitwise_xor(a, b).tobytes()


def encrypt_bytes(plaintext: bytes, key: bytes,
                  nonce: Optional[bytes] = None) -> bytes:
    if not key:
        raise ValueError("empty encryption key")
    nonce = nonce if nonce is not None else os.urandom(_NONCE)
    if len(nonce) != _NONCE:
        raise ValueError(f"nonce must be {_NONCE} bytes")
    ct = _keystream_xor(plaintext, key, nonce)
    mac_key = _derive(key, b"mac")
    tag = hashlib.blake2b(nonce + ct, key=mac_key,
                          digest_size=_TAG).digest()
    return _MAGIC + nonce + ct + tag


def is_encrypted(blob: bytes) -> bool:
    return blob[:len(_MAGIC)] == _MAGIC


def decrypt_bytes(blob: bytes, key: bytes) -> bytes:
    if not is_encrypted(blob):
        raise DecryptionError(
            "not an encrypted model blob (missing magic); load it without "
            "a key")
    body = blob[len(_MAGIC):]
    if len(body) < _NONCE + _TAG:
        raise DecryptionError("truncated encrypted blob")
    nonce = body[:_NONCE]
    ct = body[_NONCE:-_TAG]
    tag = body[-_TAG:]
    mac_key = _derive(key, b"mac")
    want = hashlib.blake2b(nonce + ct, key=mac_key,
                           digest_size=_TAG).digest()
    if not hmac.compare_digest(tag, want):
        raise DecryptionError(
            "authentication failed: wrong key or tampered file")
    return _keystream_xor(ct, key, nonce)


class Cipher:
    """reference: framework/io/crypto/cipher.h Cipher interface —
    Encrypt/Decrypt over strings and files."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        return encrypt_bytes(plaintext, key)

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        return decrypt_bytes(ciphertext, key)

    def encrypt_to_file(self, plaintext: bytes, key: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read(), key)


class CipherFactory:
    """reference: cipher.h CipherFactory::CreateCipher; config files are
    unnecessary here — one authenticated scheme, keyed at call time."""

    @staticmethod
    def create_cipher(config_fname: str = "") -> Cipher:
        return Cipher()


def encrypt_file(src: str, dst: str, key: bytes):
    with open(src, "rb") as f:
        data = f.read()
    with open(dst, "wb") as f:
        f.write(encrypt_bytes(data, key))


def decrypt_file(src: str, dst: str, key: bytes):
    with open(src, "rb") as f:
        data = f.read()
    with open(dst, "wb") as f:
        f.write(decrypt_bytes(data, key))
