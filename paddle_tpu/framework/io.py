"""Checkpoint save/load.

Reference: python/paddle/framework/io.py:553 (save), :769 (load) — pickle of
nested state_dicts with Tensor→numpy conversion. Kept byte-compatible in
spirit (pickle of numpy arrays). The sharded/async/reshard-on-load
checkpoint path for distributed training is paddle_tpu.distributed
.checkpoint (orbax-backed; see TrainStep.save_sharded/load_sharded).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.data)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4,
         encryption_key: bytes = None, **configs):
    """``encryption_key`` writes an encrypted blob (reference:
    fluid/framework/io/crypto/cipher.h model crypto; here the
    authenticated scheme in framework.crypto)."""
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    if encryption_key is not None:
        from .crypto import encrypt_bytes
        payload = encrypt_bytes(
            pickle.dumps(_to_saveable(obj), protocol=protocol),
            encryption_key)
        with open(path, "wb") as f:
            f.write(payload)
        return
    # unencrypted: stream straight to disk (no full-blob materialization)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, encryption_key: bytes = None, **configs) -> Any:
    from .crypto import _MAGIC, decrypt_bytes
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            # plain pickle: stream (a needless key is simply unused)
            f.seek(0)
            return pickle.load(f)
        if encryption_key is None:
            raise ValueError(
                f"{path!r} is an encrypted model file — pass "
                "encryption_key= to paddle.load")
        payload = head + f.read()
    return pickle.loads(decrypt_bytes(payload, encryption_key))
