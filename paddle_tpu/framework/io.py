"""Checkpoint save/load.

Reference: python/paddle/framework/io.py:553 (save), :769 (load) — pickle of
nested state_dicts with Tensor→numpy conversion. Kept byte-compatible in
spirit (pickle of numpy arrays). The sharded/async/reshard-on-load
checkpoint path for distributed training is paddle_tpu.distributed
.checkpoint (orbax-backed; see TrainStep.save_sharded/load_sharded).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.data)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, **configs) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)
