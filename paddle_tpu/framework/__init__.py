from .io import load, save  # noqa: F401
