"""paddle.compat string helpers (reference: python/paddle/compat.py)."""

__all__ = ["to_text", "to_bytes", "long_type", "floor_division",
           "get_exception_message"]

long_type = int


def to_text(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, (list, set)):
        return type(obj)(to_text(o, encoding) for o in obj)
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return str(obj) if not isinstance(obj, str) else obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, (list, set)):
        return type(obj)(to_bytes(o, encoding) for o in obj)
    if isinstance(obj, str):
        return obj.encode(encoding)
    return bytes(obj) if not isinstance(obj, bytes) else obj


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
