"""Hierarchical embedding tiering: HBM hot tier ← host ← SSD.

reference parity: ssd_sparse_table.h's memory-cache-over-rocksdb, and
Monolith's collisionless hot-ID tables — the observation both encode is
that recsys id traffic is power-law: a tiny hot set takes almost every
hit, so the hot rows must live at device speed while the long tail
spills down the hierarchy.

Design: :class:`TieredEmbeddingTable` owns an HBM-resident hot tier (a
device array of ``hot_rows`` slots + a host-side id→slot map) fronting
a *backing* table — by default an
:class:`~paddle_tpu.distributed.ps.SSDSparseTable`, whose own LRU cache
is the HOST tier and whose log-structured file is the SSD tier, giving
the full HBM ← host ← SSD ladder; any SparseTable-protocol object
(e.g. a plain host :class:`SparseTable`) works as a two-tier stack.

Row residency is EXCLUSIVE (Monolith-style): a row lives in exactly one
tier; promotion moves it up (raw read incl. optimizer state via
``read_rows``), demotion writes it back verbatim (``write_rows`` — no
gradient math on the move). Admission is by access frequency (a row is
promoted after ``admit_after`` pulls), eviction is LRU over the hot
slots. Pulls and pushes keep SparseTable's semantics: duplicate-id
gradients accumulate over the unique set before the row update, hot
rows update ON DEVICE with the same adagrad/sgd formulas.

Per-tier hit/miss/promotion counters stream through ``monitor/``
(:meth:`publish_tier_metrics`; rendered by
``tools/monitor_report.py --recsys``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["TieredEmbeddingTable"]


class TieredEmbeddingTable:
    """SparseTable-protocol table whose hot rows live in device memory.

    ``hot_rows`` caps HBM residency; ``admit_after`` is the access
    frequency that earns a row promotion (1 = admit on first touch).
    """

    def __init__(self, num_rows: int, dim: int, hot_rows: int = 4096,
                 backing=None, host_rows: Optional[int] = None,
                 admit_after: int = 2, optimizer: str = "adagrad",
                 lr: float = 0.05, seed: int = 0, name: str = "table"):
        if optimizer not in ("adagrad", "sgd"):
            raise ValueError(f"unknown PS optimizer {optimizer!r}")
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.hot_rows = max(1, int(hot_rows))
        self.admit_after = max(1, int(admit_after))
        self.optimizer = optimizer
        self.lr = float(lr)
        self.name = name
        self._own_backing = backing is None
        if backing is None:
            from ..distributed.ps import SSDSparseTable
            backing = SSDSparseTable(
                num_rows, dim,
                cache_rows=host_rows if host_rows is not None
                else max(4 * self.hot_rows, 1024),
                optimizer=optimizer, lr=lr, seed=seed)
        if getattr(backing, "optimizer", optimizer) != optimizer or \
                getattr(backing, "lr", lr) != lr:
            raise ValueError(
                "tier optimizer/lr must match the backing table's (a "
                "row's update rule cannot depend on which tier it is in)")
        self.backing = backing
        self._hot = jnp.zeros((self.hot_rows, self.dim), jnp.float32)
        self._hot_g2 = jnp.zeros((self.hot_rows,), jnp.float32)
        self._slot_of: Dict[int, int] = {}
        self._lru: "OrderedDict[int, int]" = OrderedDict()   # rid -> slot
        self._free: List[int] = list(range(self.hot_rows - 1, -1, -1))
        self._freq: Dict[int, int] = {}
        #: hot rows updated since promotion — only these need the
        #: demotion write-back (a clean row's backing copy is current)
        self._dirty: set = set()
        self.pull_count = 0
        self.push_count = 0
        self.stats = {"hbm_hits": 0, "host_hits": 0, "ssd_reads": 0,
                      "lazy_inits": 0, "promotions": 0, "demotions": 0,
                      "evictions": 0}
        self._published = dict(self.stats)
        self.bytes_pulled = 0
        self.bytes_pushed = 0
        self.ids_seen = 0
        self.rows_fetched = 0

    # -- hot-tier bookkeeping ----------------------------------------------
    def _touch(self, rid: int) -> None:
        self._lru.move_to_end(rid)

    def _backing_read_stats(self):
        b = self.backing
        return (getattr(b, "cache_hit_count", None),
                getattr(b, "log_read_count", 0),
                getattr(b, "lazy_init_count", 0))

    def _count_backing(self, before, n_rows: int) -> None:
        """Attribute ``n_rows`` backing fetches to host/ssd tiers from
        the backing table's read-source counters (SSDSparseTable); a
        plain host table attributes everything to the host tier."""
        after = self._backing_read_stats()
        if before[0] is None or after[0] is None:
            self.stats["host_hits"] += n_rows
            return
        self.stats["host_hits"] += after[0] - before[0]
        self.stats["ssd_reads"] += after[1] - before[1]
        self.stats["lazy_inits"] += after[2] - before[2]

    def _evict_one(self) -> int:
        """Free the LRU hot slot. Only a DIRTY row (updated while hot)
        is demoted — written back verbatim (value + optimizer state, no
        gradient math); a clean row's backing copy is still current, so
        its eviction costs no I/O. Hence evictions >= demotions."""
        rid, slot = self._lru.popitem(last=False)
        del self._slot_of[rid]
        self.stats["evictions"] += 1
        if rid in self._dirty:
            self._dirty.discard(rid)
            vec = np.asarray(self._hot[slot]).reshape(1, self.dim)
            g2 = np.asarray(self._hot_g2[slot]).reshape(1)
            self.backing.write_rows([rid], vec, g2)
            self.stats["demotions"] += 1
        return slot

    def _attribute_raw_reads(self, rids: List[int]) -> None:
        """Per-tier attribution of promotion reads (read_rows bypasses
        the backing's own counters): probe residency directly for an
        SSD backing; anything else attributes to the host tier."""
        b = self.backing
        cache = getattr(b, "_cache", None)
        index = getattr(b, "_index", None)
        if cache is None or getattr(b, "num_shards", 1) != 1:
            self.stats["host_hits"] += len(rids)
            return
        for rid in rids:
            if rid in cache:
                self.stats["host_hits"] += 1
            elif index is not None and rid in index:
                self.stats["ssd_reads"] += 1
            else:
                self.stats["lazy_inits"] += 1

    def _insert_hot(self, rids: List[int], vecs: np.ndarray,
                    g2: np.ndarray) -> None:
        """Install already-read rows into the hot tier, evicting LRU
        rows as needed. A batch larger than the free-slot count commits
        row by row: an eviction mid-batch reads the hot array, so every
        earlier insertion of THIS batch must already be written (a
        batched write would demote stale slot contents)."""
        if not rids:
            return
        if len(rids) <= len(self._free):
            slots = []
            for rid in rids:
                slot = self._free.pop()
                self._slot_of[rid] = slot
                self._lru[rid] = slot
                slots.append(slot)
            idx = jnp.asarray(np.asarray(slots, np.int32))
            self._hot = self._hot.at[idx].set(jnp.asarray(vecs))
            self._hot_g2 = self._hot_g2.at[idx].set(jnp.asarray(g2))
        else:
            for i, rid in enumerate(rids):
                slot = (self._free.pop() if self._free
                        else self._evict_one())
                self._slot_of[rid] = slot
                self._lru[rid] = slot
                self._hot = self._hot.at[slot].set(jnp.asarray(vecs[i]))
                self._hot_g2 = self._hot_g2.at[slot].set(float(g2[i]))
        self.stats["promotions"] += len(rids)

    def _age_freq(self) -> None:
        """Bound the frequency map: when it outgrows the hot set by a
        wide margin, drop the single-touch tail (power-law traffic
        keeps genuinely hot ids above 1)."""
        if len(self._freq) > max(65536, 16 * self.hot_rows):
            self._freq = {r: c for r, c in self._freq.items() if c > 1}

    # -- SparseTable protocol ----------------------------------------------
    def pull(self, ids) -> np.ndarray:
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        self.pull_count += 1
        uniq, inv = np.unique(ids_np, return_inverse=True)
        self.ids_seen += ids_np.size
        self.rows_fetched += uniq.size
        self.bytes_pulled += uniq.size * self.dim * 4
        hot_ids, hot_pos, cold_ids, cold_pos = [], [], [], []
        for i, rid in enumerate(uniq):
            rid = int(rid)
            c = self._freq.get(rid, 0) + 1
            self._freq[rid] = c
            if rid in self._slot_of:
                self._touch(rid)
                hot_ids.append(rid)
                hot_pos.append(i)
            else:
                cold_ids.append(rid)
                cold_pos.append(i)
        out = np.empty((uniq.size, self.dim), np.float32)
        if hot_ids:
            self.stats["hbm_hits"] += len(hot_ids)
            slots = np.asarray([self._slot_of[r] for r in hot_ids],
                               np.int32)
            out[hot_pos] = np.asarray(self._hot[jnp.asarray(slots)])
        if cold_ids:
            # promotion-bound rows are read ONCE via the raw surface
            # (value + optimizer state together) and never enter the
            # backing's LRU — a row moving to HBM must not evict a
            # genuine host-tier row on its way out
            pos_of = dict(zip(cold_ids, cold_pos))
            promote = [r for r in cold_ids
                       if self._freq[r] >= self.admit_after]
            stay = [r for r in cold_ids
                    if self._freq[r] < self.admit_after]
            if stay:
                before = self._backing_read_stats()
                out[[pos_of[r] for r in stay]] = self.backing.pull(stay)
                self._count_backing(before, len(stay))
            if promote:
                self._attribute_raw_reads(promote)
                vecs, g2 = self.backing.read_rows(promote)
                out[[pos_of[r] for r in promote]] = vecs
                self._insert_hot(promote, vecs, g2)
        self._age_freq()
        return out[inv]

    def lookup(self, ids) -> jnp.ndarray:
        """Device-array lookup; when EVERY unique id is hot the rows
        come straight off the device array — the hot set serves at
        device speed with no host round-trip."""
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        uniq, inv = np.unique(ids_np, return_inverse=True)
        if all(int(r) in self._slot_of for r in uniq):
            self.pull_count += 1
            self.ids_seen += ids_np.size
            self.rows_fetched += uniq.size
            self.bytes_pulled += uniq.size * self.dim * 4
            self.stats["hbm_hits"] += uniq.size
            slots = np.empty(uniq.size, np.int32)
            for i, rid in enumerate(uniq):
                rid = int(rid)
                self._freq[rid] = self._freq.get(rid, 0) + 1
                self._touch(rid)
                slots[i] = self._slot_of[rid]
            return self._hot[jnp.asarray(slots)][jnp.asarray(
                inv.astype(np.int32))]
        return jnp.asarray(self.pull(ids_np))

    def push(self, ids, grads) -> None:
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        grads_np = np.asarray(grads, np.float32).reshape(
            ids_np.size, self.dim)
        self.push_count += 1
        self.bytes_pushed += grads_np.nbytes
        uniq, inv = np.unique(ids_np, return_inverse=True)
        acc = np.zeros((uniq.size, self.dim), np.float32)
        np.add.at(acc, inv, grads_np)
        hot_slots, hot_rows, cold_ids, cold_rows = [], [], [], []
        for i, rid in enumerate(uniq):
            rid = int(rid)
            slot = self._slot_of.get(rid)
            if slot is not None:
                self._touch(rid)
                self._dirty.add(rid)      # backing copy is now stale
                hot_slots.append(slot)
                hot_rows.append(i)
            else:
                cold_ids.append(rid)
                cold_rows.append(i)
        if hot_slots:
            idx = jnp.asarray(np.asarray(hot_slots, np.int32))
            a = jnp.asarray(acc[hot_rows])
            if self.optimizer == "adagrad":
                g2 = self._hot_g2.at[idx].add((a ** 2).mean(axis=1))
                denom = jnp.sqrt(g2[idx])[:, None] + 1e-10
                self._hot = self._hot.at[idx].add(-self.lr * a / denom)
                self._hot_g2 = g2
            else:
                self._hot = self._hot.at[idx].add(-self.lr * a)
        if cold_ids:
            self.backing.push(cold_ids, acc[cold_rows])

    # -- accounting / reporting --------------------------------------------
    @property
    def dedup_ratio(self) -> float:
        """Mean ids-per-fetched-row since construction (1.0 = no
        reuse; power-law traffic sits well above it)."""
        return self.ids_seen / self.rows_fetched if self.rows_fetched \
            else 1.0

    @property
    def resident_hot_rows(self) -> int:
        return len(self._slot_of)

    def device_arrays(self):
        out = [self._hot]
        if self.optimizer == "adagrad":
            out.append(self._hot_g2)
        return out

    def hbm_bytes(self) -> int:
        return sum(int(a.nbytes) for a in self.device_arrays())

    def tier_rows(self) -> Dict[str, int]:
        """Resident row counts per tier (occupancy view)."""
        out = {"hbm": len(self._slot_of)}
        b = self.backing
        if hasattr(b, "resident_rows"):
            out["host"] = int(b.resident_rows)
            out["ssd"] = int(getattr(b, "spilled_rows", 0))
        else:
            out["host"] = int(getattr(b, "num_rows", 0))
        return out

    def hit_rates(self) -> Dict[str, float]:
        """Per-tier share of row fetches, in percent (lazy inits count
        as SSD-tier reads: the row's home is the log)."""
        s = self.stats
        total = (s["hbm_hits"] + s["host_hits"] + s["ssd_reads"]
                 + s["lazy_inits"])
        if not total:
            return {"hbm": 0.0, "host": 0.0, "ssd": 0.0}
        return {"hbm": 100.0 * s["hbm_hits"] / total,
                "host": 100.0 * s["host_hits"] / total,
                "ssd": 100.0 * (s["ssd_reads"] + s["lazy_inits"]) / total}

    def publish_tier_metrics(self, registry=None) -> None:
        """Tier counters + occupancy gauges into the metrics registry
        (delta-increments since the last publish, so repeated calls are
        idempotent over the counter streams)."""
        from ..monitor import get_registry
        reg = registry or get_registry()
        s, p = self.stats, self._published
        hits = reg.counter(
            "recsys_tier_hits_total",
            "embedding row fetches by the tier that served them")
        for tier, keys in (("hbm", ("hbm_hits",)),
                           ("host", ("host_hits",)),
                           ("ssd", ("ssd_reads", "lazy_inits"))):
            delta = sum(s[k] - p[k] for k in keys)
            if delta:
                hits.inc(delta, table=self.name, tier=tier)
        # emits-metrics: recsys_tier_promotions_total,
        # emits-metrics: recsys_tier_demotions_total,
        # emits-metrics: recsys_tier_evictions_total
        for metric, key, help_ in (
                ("recsys_tier_promotions_total", "promotions",
                 "rows promoted into the HBM hot tier"),
                ("recsys_tier_demotions_total", "demotions",
                 "rows written back to the backing tier on eviction"),
                ("recsys_tier_evictions_total", "evictions",
                 "LRU evictions from the HBM hot tier")):
            delta = s[key] - p[key]
            if delta:
                reg.counter(metric, help_).inc(delta, table=self.name)
        self._published = dict(s)
        rows = reg.gauge("recsys_table_rows",
                         "resident embedding rows per tier")
        for tier, n in self.tier_rows().items():
            rows.set(n, table=self.name, tier=tier)
        rates = reg.gauge("recsys_tier_hit_pct",
                          "share of row fetches served per tier (%)")
        for tier, v in self.hit_rates().items():
            rates.set(v, table=self.name, tier=tier)

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        """Flat arrays: the hot tier verbatim + the backing state under
        a ``backing.`` prefix (round-trips through load_state_dict;
        residency — which rows are hot — survives the trip). Rows never
        UPDATED are not materialized anywhere (clean evictions skip the
        write-back), so they re-derive from the backing's deterministic
        initializer — restore onto a table built with the SAME seed,
        the SSDSparseTable state_dict contract."""
        hot_ids = np.asarray(list(self._lru.keys()), np.int64)
        slots = np.asarray([self._lru[int(r)] for r in hot_ids], np.int32)
        out = {"hot_ids": hot_ids,
               "hot_data": np.asarray(self._hot)[slots]
               if hot_ids.size else np.zeros((0, self.dim), np.float32),
               "hot_g2": np.asarray(self._hot_g2)[slots]
               if hot_ids.size else np.zeros((0,), np.float32)}
        for k, v in self.backing.state_dict().items():
            out[f"backing.{k}"] = v
        return out

    def load_state_dict(self, state) -> None:
        self.backing.load_state_dict(
            {k[len("backing."):]: v for k, v in state.items()
             if k.startswith("backing.")})
        self._hot = jnp.zeros((self.hot_rows, self.dim), jnp.float32)
        self._hot_g2 = jnp.zeros((self.hot_rows,), jnp.float32)
        self._slot_of.clear()
        self._lru.clear()
        self._dirty.clear()
        self._free = list(range(self.hot_rows - 1, -1, -1))
        hot_ids = np.asarray(state.get("hot_ids", []), np.int64)
        data = np.asarray(state.get("hot_data",
                                    np.zeros((0, self.dim))), np.float32)
        g2 = np.asarray(state.get("hot_g2", np.zeros((0,))), np.float32)
        if hot_ids.size:
            n = min(hot_ids.size, self.hot_rows)
            slots = []
            for i in range(n):
                slot = self._free.pop()
                rid = int(hot_ids[i])
                self._slot_of[rid] = slot
                self._lru[rid] = slot
                slots.append(slot)
            idx = jnp.asarray(np.asarray(slots, np.int32))
            self._hot = self._hot.at[idx].set(jnp.asarray(data[:n]))
            self._hot_g2 = self._hot_g2.at[idx].set(jnp.asarray(g2[:n]))
            # a restored hot row's backing copy (if any) predates the
            # snapshot's hot value — it must write back on eviction
            # regardless of future pushes
            self._dirty.update(int(r) for r in hot_ids[:n])
            if hot_ids.size > self.hot_rows:
                # a smaller hot budget demotes the overflow verbatim
                self.backing.write_rows(hot_ids[n:], data[n:], g2[n:])

    def close(self) -> None:
        if self._own_backing and hasattr(self.backing, "close"):
            self.backing.close()
