"""Mesh-sharded embedding table with dedup lookups and colocated
sparse-optimizer state.

reference parity: fluid/distributed SparseTable shards behind brpc
pull_sparse/push_sparse — rows live where their shard is, gradients
travel to the rows. TPU-native redesign: the shards are MESH shards
(row-sharded over the ``ps`` axis), a lookup is one gather + one psum
inside a ``shard_map`` manual program (the PR 9/10 manual-collectives
recipe), and the sparse optimizer state (adagrad row accumulators)
lives NEXT TO the embedding rows it updates — the update never moves
state across the mesh.

Three dispatch modes, resolved per call (moe/nn.scan convention):

- **manual** — a ps>1 mesh is active, ``FLAGS_recsys_sharded_lookup``
  is on and the backend can compile manual-subgroup collectives
  (``manual_collectives_ok``): each shard gathers the unique rows it
  owns (ownership: ``id % n == shard``, the SparseTable convention),
  one ``psum`` over ``ps`` assembles the full batch on every shard.
- **auto** — same math on the GSPMD path (the row-sharded array keeps
  its ``P('ps', ...)`` placement and XLA inserts the collectives);
  entered via the kill switch or an incapable backend, counted through
  :func:`~paddle_tpu.recsys.note_recsys_fallback`.
- **local** — no mesh / ps absent: single-shard arrays, same code.

Dedup (``FLAGS_recsys_dedup``, default on): sort-unique the batch ids,
fetch each distinct row ONCE, inverse-permute back — duplicate ids (the
power-law hot-id regime: a handful of ids dominate every batch) cost
one row of traffic instead of one per occurrence. Gradients accumulate
over the unique set BEFORE the row update regardless of the flag (that
is SparseTable's push semantics, not an optimization); the flag only
governs gather traffic, so off = the bit-compatible per-id oracle.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.flags import get_flag
from . import RECSYS_STATS, note_recsys_fallback

__all__ = ["PS_AXIS", "ShardedEmbeddingTable"]

PS_AXIS = "ps"


def _pad_len(n: int) -> int:
    """Pow2 bucket ≥ 8 so the manual program compiles once per bucket,
    not once per batch's unique-id count."""
    p = 8
    while p < n:
        p *= 2
    return p


class ShardedEmbeddingTable:
    """Device-resident embedding shards over the mesh ``ps`` axis.

    Protocol-compatible with :class:`~paddle_tpu.distributed.ps.
    SparseTable` (``pull``/``push``/``state_dict``), so
    ``DistributedEmbedding(table=...)`` and the tier manager work
    unchanged; :meth:`lookup` / :meth:`apply_grads` are the device-array
    fast path the DLRM model and the serving engine use."""

    def __init__(self, num_rows: int, dim: int, optimizer: str = "adagrad",
                 lr: float = 0.05, seed: int = 0, axis: str = PS_AXIS,
                 initializer=None):
        if optimizer not in ("adagrad", "sgd"):
            raise ValueError(f"unknown PS optimizer {optimizer!r}")
        from ..distributed import env as dist_env
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.seed = int(seed)
        self.axis = axis
        self._mesh = dist_env.get_mesh()
        if self._mesh is not None and axis in self._mesh.axis_names:
            self.num_shards = int(self._mesh.shape[axis])
        else:
            self._mesh = None
            self.num_shards = 1
        n = self.num_shards
        self._rows_per_shard = (self.num_rows + n - 1) // n
        scale = 1.0 / np.sqrt(self.dim)
        shards = []
        for s in range(n):
            local = (self.num_rows + n - 1 - s) // n
            if initializer is not None:
                block = np.asarray(initializer(local, self.dim),
                                   np.float32)
            else:
                # per-shard rng stream == SparseTable(shard_id=s): a
                # 1-shard table matches SparseTable(seed) bit-for-bit
                rng = np.random.default_rng(self.seed + s)
                block = rng.uniform(-scale, scale,
                                    (local, self.dim)).astype(np.float32)
            if local < self._rows_per_shard:
                block = np.concatenate(
                    [block, np.zeros((self._rows_per_shard - local,
                                      self.dim), np.float32)])
            shards.append(block)
        data = np.stack(shards)                       # [n, R, D]
        g2 = np.zeros((n, self._rows_per_shard), np.float32)
        if self._mesh is not None:
            self.data = jax.device_put(
                data, NamedSharding(self._mesh, P(axis, None, None)))
            self.g2 = jax.device_put(
                g2, NamedSharding(self._mesh, P(axis, None)))
        else:
            self.data = jnp.asarray(data)
            self.g2 = jnp.asarray(g2)
        self._lookup_progs: Dict[tuple, object] = {}
        self._update_progs: Dict[tuple, object] = {}
        self.pull_count = 0
        self.push_count = 0
        self.ids_seen = 0
        self.rows_fetched = 0
        self.bytes_pulled = 0
        self.bytes_pushed = 0

    # -- dispatch-mode resolution ------------------------------------------
    def _mode(self) -> str:
        if self._mesh is None or self.num_shards == 1:
            return "local"
        if not bool(get_flag("recsys_sharded_lookup")):
            note_recsys_fallback("flag_off")
            return "auto"
        from ..distributed.meta_parallel.spmd_pipeline import (
            manual_collectives_ok)
        if not manual_collectives_ok(self._mesh, self.axis):
            note_recsys_fallback(
                "backend_mesh",
                f"backend={jax.default_backend()} "
                f"mesh={dict(self._mesh.shape)}")
            return "auto"
        return "manual"

    def _check_ids(self, ids) -> np.ndarray:
        """Range-validate BOTH surfaces: the manual update program clips
        local indices (a pad-row necessity), so an out-of-range id would
        silently update the wrong row on one dispatch mode and scatter-
        drop on the other — reject it loudly instead, like SparseTable's
        wrong-shard check."""
        ids_np = np.asarray(ids, np.int64).reshape(-1)
        if ids_np.size and (ids_np.min() < 0
                            or ids_np.max() >= self.num_rows):
            raise ValueError(
                f"embedding ids outside [0, {self.num_rows})")
        return ids_np

    def _dedup(self, ids: np.ndarray):
        """(uniq, inv) under the dedup flag; flag off = identity (the
        per-id gather oracle). Accounting feeds the bench's dedup
        ratio: ids_seen / rows_fetched."""
        self.ids_seen += ids.size
        if bool(get_flag("recsys_dedup")):
            uniq, inv = np.unique(ids, return_inverse=True)
        else:
            uniq, inv = ids, np.arange(ids.size)
        self.rows_fetched += uniq.size
        return uniq.astype(np.int64), inv.reshape(-1)

    @property
    def dedup_ratio(self) -> float:
        """Mean ids-per-fetched-row since construction (1.0 = no reuse)."""
        return self.ids_seen / self.rows_fetched if self.rows_fetched \
            else 1.0

    # -- lookup -------------------------------------------------------------
    def lookup(self, ids) -> jnp.ndarray:
        """Rows for ``ids`` as a device array ``[N, dim]`` (any leading
        shape flattens; the caller reshapes). One unique-row gather +
        inverse permute under the dedup flag."""
        ids_np = self._check_ids(ids)
        self.pull_count += 1
        uniq, inv = self._dedup(ids_np)
        self.bytes_pulled += uniq.size * self.dim * 4
        rows = self._gather_unique(uniq)
        return rows[jnp.asarray(inv, jnp.int32)]

    def _gather_unique(self, uniq: np.ndarray) -> jnp.ndarray:
        mode = self._mode()
        n = self.num_shards
        if mode == "manual":
            RECSYS_STATS["manual_lookups"] += 1
            U = _pad_len(max(1, uniq.size))
            pad_val = int(uniq[0]) if uniq.size else 0
            padded = np.full((U,), pad_val, np.int64)
            padded[:uniq.size] = uniq
            prog = self._lookup_prog(U)
            rows = prog(self.data, jnp.asarray(padded, jnp.int32))
            return rows[:uniq.size]
        RECSYS_STATS["auto_lookups"] += 1
        u = jnp.asarray(uniq, jnp.int32)
        return self.data[u % n, u // n]

    def _lookup_prog(self, U: int):
        key = (id(self._mesh), U)
        prog = self._lookup_progs.get(key)
        if prog is not None:
            return prog
        from ..distributed import env as dist_env
        n, axis = self.num_shards, self.axis

        def body(data_s, shard_s, uniq):
            s = shard_s[0]
            own = (uniq % n) == s
            local = jnp.clip(uniq // n, 0, data_s.shape[1] - 1)
            rows = jnp.where(own[:, None], data_s[0, local], 0.0)
            return jax.lax.psum(rows, axis)

        shard_ids = jax.device_put(
            np.arange(n, dtype=np.int32),
            NamedSharding(self._mesh, P(axis)))
        prog = jax.jit(dist_env.shard_map(
            body, mesh=self._mesh,
            in_specs=(P(axis, None, None), P(axis), P()),
            out_specs=P(), axis_names={axis}, check_vma=False))
        wrapped = lambda data, uniq: prog(data, shard_ids, uniq)
        self._lookup_progs[key] = wrapped
        return wrapped

    # -- sparse update ------------------------------------------------------
    def apply_grads(self, ids, grads) -> None:
        """Sparse optimizer step: accumulate duplicate-id gradients over
        the unique set (SparseTable push semantics, np accumulation
        order), then the row-wise adagrad/sgd update runs ON the shard
        that owns each row — optimizer state never crosses the mesh."""
        ids_np = self._check_ids(ids)
        grads_np = np.asarray(grads, np.float32).reshape(
            ids_np.size, self.dim)
        self.push_count += 1
        self.bytes_pushed += grads_np.nbytes
        uniq, inv = np.unique(ids_np, return_inverse=True)
        acc = np.zeros((uniq.size, self.dim), np.float32)
        np.add.at(acc, inv, grads_np)
        self._update_unique(uniq.astype(np.int64), acc)

    def _update_unique(self, uniq: np.ndarray, acc: np.ndarray) -> None:
        mode = self._mode()
        n = self.num_shards
        if mode == "manual":
            RECSYS_STATS["manual_updates"] += 1
            U = _pad_len(max(1, uniq.size))
            pad_val = int(uniq[0]) if uniq.size else 0
            padded_ids = np.full((U,), pad_val, np.int64)
            padded_ids[:uniq.size] = uniq
            padded_acc = np.zeros((U, self.dim), np.float32)
            padded_acc[:uniq.size] = acc
            prog = self._update_prog(U)
            self.data, self.g2 = prog(
                self.data, self.g2, jnp.asarray(padded_ids, jnp.int32),
                jnp.asarray(padded_acc))
            return
        RECSYS_STATS["auto_updates"] += 1
        u = jnp.asarray(uniq, jnp.int32)
        shard, local = u % n, u // n
        a = jnp.asarray(acc)
        if self.optimizer == "adagrad":
            g2 = self.g2.at[shard, local].add((a ** 2).mean(axis=1))
            denom = jnp.sqrt(g2[shard, local])[:, None] + 1e-10
            self.data = self.data.at[shard, local].add(
                -self.lr * a / denom)
            self.g2 = g2
        else:
            self.data = self.data.at[shard, local].add(-self.lr * a)

    def _update_prog(self, U: int):
        key = (id(self._mesh), U)
        prog = self._update_progs.get(key)
        if prog is not None:
            return prog
        from ..distributed import env as dist_env
        n, axis, lr = self.num_shards, self.axis, self.lr
        adagrad = self.optimizer == "adagrad"

        def body(data_s, g2_s, shard_s, uniq, acc):
            s = shard_s[0]
            own = (uniq % n) == s
            local = jnp.clip(uniq // n, 0, data_s.shape[1] - 1)
            if adagrad:
                # pad entries carry zero acc: their .add is a no-op,
                # and pad-vs-real duplicates of the same row read the
                # SAME final g2, so the real entry's denom is exact
                msq = jnp.where(own, (acc ** 2).mean(axis=1), 0.0)
                g2n = g2_s[0].at[local].add(msq)
                denom = jnp.sqrt(g2n[local])[:, None] + 1e-10
                upd = jnp.where(own[:, None], -lr * acc / denom, 0.0)
                return (data_s[0].at[local].add(upd)[None],
                        g2n[None])
            upd = jnp.where(own[:, None], -lr * acc, 0.0)
            return data_s[0].at[local].add(upd)[None], g2_s

        # donation keeps the update at ONE table copy in HBM — but the
        # jax 0.4.37 cpu+persistent-cache reload drops input-output
        # aliasing from donated executables (the PR 2 hazard, observed
        # here on shard_map programs too): warm-cache updates read
        # clobbered rows. _donation_safe gates exactly that backend.
        from ..jit.to_static import _donation_safe
        shard_ids = jax.device_put(
            np.arange(n, dtype=np.int32),
            NamedSharding(self._mesh, P(axis)))
        prog = jax.jit(dist_env.shard_map(
            body, mesh=self._mesh,
            in_specs=(P(axis, None, None), P(axis, None), P(axis),
                      P(), P()),
            out_specs=(P(axis, None, None), P(axis, None)),
            axis_names={axis}, check_vma=False),
            donate_argnums=(0, 1) if _donation_safe() else ())
        wrapped = lambda data, g2, uniq, acc: prog(data, g2, shard_ids,
                                                   uniq, acc)
        self._update_progs[key] = wrapped
        return wrapped

    # -- SparseTable protocol (host arrays) ---------------------------------
    def pull(self, ids) -> np.ndarray:
        return np.asarray(self.lookup(ids))

    def push(self, ids, grads) -> None:
        self.apply_grads(ids, grads)

    # -- accounting / attribution -------------------------------------------
    def device_arrays(self):
        """Live device buffers for the HBM census
        (:func:`paddle_tpu.recsys.publish_table_hbm`)."""
        out = [self.data]
        if self.optimizer == "adagrad":
            out.append(self.g2)
        return out

    def hbm_bytes(self) -> int:
        return sum(int(a.nbytes) for a in self.device_arrays())

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        """Global-row-order dense arrays (mesh-layout-independent: a
        checkpoint written on ps=8 restores onto ps=2 or ps=1)."""
        arr = np.asarray(self.data)             # [n, R, D]
        g2 = np.asarray(self.g2)
        ids = np.arange(self.num_rows)
        out = {"data": arr[ids % self.num_shards, ids // self.num_shards]}
        if self.optimizer == "adagrad":
            out["g2"] = g2[ids % self.num_shards, ids // self.num_shards]
        return out

    def load_state_dict(self, state) -> None:
        data = np.asarray(state["data"], np.float32)
        if data.shape != (self.num_rows, self.dim):
            raise ValueError(
                f"state_dict shape {data.shape} != table "
                f"{(self.num_rows, self.dim)}")
        n, R = self.num_shards, self._rows_per_shard
        arr = np.zeros((n, R, self.dim), np.float32)
        ids = np.arange(self.num_rows)
        arr[ids % n, ids // n] = data
        g2 = np.zeros((n, R), np.float32)
        if "g2" in state and self.optimizer == "adagrad":
            g2[ids % n, ids // n] = np.asarray(state["g2"], np.float32)
        if self._mesh is not None:
            self.data = jax.device_put(
                arr, NamedSharding(self._mesh, P(self.axis, None, None)))
            self.g2 = jax.device_put(
                g2, NamedSharding(self._mesh, P(self.axis, None)))
        else:
            self.data = jnp.asarray(arr)
            self.g2 = jnp.asarray(g2)
