"""Giant-embedding recommender subsystem (docs/RECSYS.md).

reference parity: the reference's single largest subsystem outside the
op library is the 22k-LoC parameter-server stack
(paddle/fluid/distributed/: brpc servers, SparseTable shards,
ssd_sparse_table.h) driving DLRM-shaped recsys traffic — embedding
tables of 10^9 rows updated sparsely, pulled at serving deadlines.
`distributed/ps/` rebuilt the host tier (SparseTable / SSDSparseTable /
DistributedEmbedding); this package makes recsys a first-class training
AND serving axis on top of it (ISSUE 12):

- :class:`~.sharded_table.ShardedEmbeddingTable` — embedding rows laid
  out ACROSS the mesh (row-sharded over the ``ps`` axis via shard_map
  manual collectives, the PR 9/10 recipe, with a GSPMD auto fallback
  counted through :func:`note_recsys_fallback`), unique/dedup lookups
  (sort-unique → one gather → inverse-permute) and sparse-grad
  optimizer state colocated with the rows it updates;
- :class:`~.tiering.TieredEmbeddingTable` — an HBM-resident hot tier
  fronting the host/SSD tables (admission by access frequency,
  eviction by LRU), so a table exceeds single-chip HBM and then host
  RAM while the hot set serves at device speed (Monolith-style hot-ID
  tiering over the ssd_table heritage);
- :class:`~.data.CriteoSynthetic` — seeded power-law workload generator
  (the criteo shape: dense floats + one id per sparse slot);
- :class:`~.serving.RecsysEngine` — online lookup + ranking riding the
  PR 6/8 serving discipline: bounded-queue admission, deadlines,
  overload shedding, lookup-latency histograms;
- :mod:`~.checkpoint` — sharded-table save/restore through the PR 5
  atomic checkpoint manifest (torn commits fall back, chaos-drilled).

The model half lives in :mod:`paddle_tpu.models.dlrm` (dense bottom
MLP, N sparse features through these tables, pairwise interaction,
top MLP — Naumov et al.).
"""

from __future__ import annotations

import warnings
from typing import Dict, Optional

__all__ = ["RECSYS_STATS", "reset_recsys_stats", "note_recsys_fallback",
           "register_table", "tables", "publish_table_hbm", "reset",
           "ShardedEmbeddingTable", "TieredEmbeddingTable",
           "CriteoSynthetic", "RecsysEngine", "RecsysRequest",
           "RecsysServingConfig", "save_tables", "load_tables"]

#: observability (the nn/scan SCAN_STATS convention): explicit mesh
#: lookups/updates, auto-path dispatches, and fallbacks (a ps>1 mesh is
#: present but the explicit shard_map program could not run).
RECSYS_STATS = {"manual_lookups": 0, "auto_lookups": 0,
                "manual_updates": 0, "auto_updates": 0, "fallbacks": 0}

_FALLBACK_WARNED: set = set()


def reset_recsys_stats() -> None:
    for k in RECSYS_STATS:
        RECSYS_STATS[k] = 0
    _FALLBACK_WARNED.clear()


def note_recsys_fallback(reason: str, detail: str = "") -> None:
    """A ps>1 mesh is active but the explicit sharded-lookup program
    degraded to the GSPMD auto path — same math, XLA places the
    collectives. One-time warning per cause + counted (monitor mode
    adds a ``recsys_fallback_total`` registry counter)."""
    RECSYS_STATS["fallbacks"] += 1
    key = (reason, detail)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        warnings.warn(
            f"recsys sharded lookup degraded to the GSPMD auto path "
            f"(reason: {reason}{'; ' + detail if detail else ''}); the "
            "math is unchanged but the explicit gather+psum program "
            "does not run. On XLA:CPU this is expected for meshes with "
            "other nontrivial axes (manual-subgroup collectives); on "
            "TPU check FLAGS_recsys_sharded_lookup and the mesh axes.",
            RuntimeWarning, stacklevel=3)
    from ..monitor import enabled as _mon_enabled
    if _mon_enabled():
        from ..monitor import get_registry
        get_registry().counter(
            "recsys_fallback_total",
            "ps meshes that degraded to the GSPMD auto path, by cause",
        ).inc(reason=reason)


# ---------------------------------------------------------------------------
# Table registry: monitor_report --recsys and the HBM attribution walk
# name every live table through here (reset() clears it between tests).
# ---------------------------------------------------------------------------

_TABLES: "Dict[str, object]" = {}


def register_table(name: str, table) -> None:
    _TABLES[name] = table


def tables() -> Dict[str, object]:
    return dict(_TABLES)


def publish_table_hbm(registry=None) -> Dict[str, int]:
    """Per-table HBM attribution (the PR 4 census discipline applied to
    embedding tables): every registered table reports the DEVICE bytes
    its hot/sharded arrays pin, cross-checked against ``jax.
    live_arrays()`` by buffer identity so a dropped-but-registered
    table attributes 0, not its configured capacity. Publishes
    ``recsys_table_hbm_bytes{table=...}`` gauges; returns {name: bytes}."""
    import jax
    live = {id(a) for a in jax.live_arrays()}
    out: Dict[str, int] = {}
    for name, t in _TABLES.items():
        arrs = getattr(t, "device_arrays", lambda: [])()
        out[name] = sum(int(a.nbytes) for a in arrs if id(a) in live)
    if out:
        from ..monitor import get_registry
        g = (registry or get_registry()).gauge(
            "recsys_table_hbm_bytes",
            "device bytes pinned by a registered embedding table's "
            "hot/sharded arrays (live-buffer identity census)")
        for name, b in out.items():
            g.set(b, table=name)
    return out


def reset() -> None:
    """Test isolation: clear table registry + stats, close tables that
    own temp SSD files, and drop any live recsys serving engines."""
    for t in _TABLES.values():
        close = getattr(t, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
    _TABLES.clear()
    reset_recsys_stats()
    from .serving import reset_engines
    reset_engines()


from .sharded_table import ShardedEmbeddingTable  # noqa: E402
from .tiering import TieredEmbeddingTable  # noqa: E402
from .data import CriteoSynthetic  # noqa: E402
from .serving import (RecsysEngine, RecsysRequest,  # noqa: E402
                      RecsysServingConfig)
from .checkpoint import load_tables, save_tables  # noqa: E402
