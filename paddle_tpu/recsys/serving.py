"""Online recsys serving: batched lookup + ranking under the serving
discipline.

Recsys inference is the latency-critical half of the workload: a
request carries a user context and K candidate items, the engine must
return ranked scores inside a deadline, and under overload it must
shed load EARLY (a recommendation delivered late is worthless — unlike
an LLM token stream there is nothing to resume). This engine rides the
PR 6/8 serving machinery rather than reinventing it:

- **admission control**: a bounded queue with the reject-new /
  drop-oldest policies; refused submits raise the same typed
  :class:`~paddle_tpu.serving.resilience.ServerOverloaded` the LLM
  engine raises, and the queue-delay EWMA
  :class:`~paddle_tpu.serving.resilience.OverloadDetector` (enter/exit
  hysteresis, idle decay at submit) flips the engine into a shedding
  state;
- **deadlines**: queued requests past their deadline expire at the
  iteration boundary BEFORE any table row is fetched; completions
  observe their slack into ``recsys_deadline_slack_seconds``;
- **batched dedup lookups**: one engine step stacks every admitted
  request's candidates into ONE model forward, so the embedding pull
  dedups across requests (hot ids shared between users cost one row);
- **telemetry**: ``recsys_lookup_seconds`` / ``recsys_rank_seconds``
  (the model's embedding-vs-MLP wall split), e2e latency, request
  outcome counters, queue/overload gauges — and each step republishes
  the tier hit/occupancy metrics of every table that has them
  (``tools/monitor_report.py --recsys`` renders the lot).
"""

from __future__ import annotations

import itertools
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..monitor import get_registry
from ..serving.resilience import OverloadDetector, ServerOverloaded

__all__ = ["RecsysRequest", "RecsysResult", "RecsysServingConfig",
           "RecsysEngine", "reset_engines"]

_req_ids = itertools.count(1)
_LIVE_ENGINES: "weakref.WeakSet[RecsysEngine]" = weakref.WeakSet()


@dataclass
class RecsysRequest:
    """One ranking request: a user context (dense features) and K
    candidate items, each a full sparse-slot row ``[num_sparse]``."""

    dense: np.ndarray
    candidate_ids: np.ndarray          # [K, num_sparse] int64
    deadline_s: Optional[float] = None
    priority: int = 0
    on_result: Optional[Callable] = None
    request_id: int = field(default_factory=lambda: next(_req_ids))


@dataclass
class RecsysResult:
    request_id: int
    scores: np.ndarray                 # [K] click logits
    order: np.ndarray                  # candidate indices, best first
    e2e_s: float = 0.0


class _State:
    __slots__ = ("request", "submitted_t", "deadline_t", "outcome",
                 "result", "failure")

    def __init__(self, request: RecsysRequest, now: float):
        self.request = request
        self.submitted_t = now
        self.deadline_t = (now + request.deadline_s
                           if request.deadline_s is not None else None)
        self.outcome: Optional[str] = None
        self.result: Optional[RecsysResult] = None
        self.failure: Optional[str] = None


@dataclass
class RecsysServingConfig:
    #: requests ranked per engine step (their candidates batch into one
    #: forward — the cross-request dedup window)
    max_batch: int = 8
    max_queue: int = 256
    #: bounded-queue shedding policy: reject-new | drop-oldest
    queue_policy: str = "reject-new"
    #: queue-delay EWMA overload detector (0 = off), the PR 8 shape
    overload_threshold_s: float = 0.0
    overload_alpha: float = 0.3
    overload_exit_frac: float = 0.5
    #: republish tier hit/occupancy metrics each step
    publish_tier_metrics: bool = True


class RecsysEngine:
    """Drive a :class:`~paddle_tpu.models.dlrm.DLRM` (or any model with
    ``forward(dense, ids) -> logits`` and ``last_timings``) as an
    online ranking service."""

    QUEUE_POLICIES = ("reject-new", "drop-oldest")

    def __init__(self, model, config: Optional[RecsysServingConfig] = None,
                 clock=time.perf_counter):
        self.model = model
        self.config = config or RecsysServingConfig()
        if self.config.queue_policy not in self.QUEUE_POLICIES:
            raise ValueError(
                f"unknown queue_policy {self.config.queue_policy!r}; "
                f"one of {self.QUEUE_POLICIES}")
        self.clock = clock
        self._queue: List[_State] = []
        self._overload = (OverloadDetector(
            self.config.overload_threshold_s,
            alpha=self.config.overload_alpha,
            exit_frac=self.config.overload_exit_frac)
            if self.config.overload_threshold_s > 0 else None)
        self.stats = {"submitted": 0, "completed": 0, "expired": 0,
                      "rejected": 0, "shed": 0, "failed": 0, "steps": 0,
                      "candidates_scored": 0}
        self._lat: Dict[str, List[float]] = {"e2e": [], "lookup": [],
                                             "rank": []}
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        _LIVE_ENGINES.add(self)

    # -- events --------------------------------------------------------------
    def _count(self, event: str) -> None:
        get_registry().counter(
            "recsys_requests_total",
            "recsys ranking requests by lifecycle event").inc(event=event)

    def _terminate(self, st: _State, outcome: str) -> None:
        st.outcome = outcome
        self.stats[outcome] += 1
        self._count(outcome)

    def _publish_gauges(self) -> None:
        get_registry().gauge(
            "recsys_queue_depth",
            "ranking requests waiting for an engine step").set(
            len(self._queue))

    # -- request surface -----------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def submit(self, request: RecsysRequest) -> _State:
        now = self.clock()
        if self._overload is not None and self._overload.overloaded:
            if not self._queue:
                # idle engine: fold the empty-queue delay sample here or
                # a tripped detector latches forever (the PR 8 lesson)
                transition = self._overload.observe(0.0)
                if transition is not None:
                    self._overload_transition(transition)
            if self._overload is not None and self._overload.overloaded:
                self.stats["rejected"] += 1
                self._count("rejected")
                raise ServerOverloaded(
                    "overload", queue_depth=len(self._queue),
                    ewma_s=self._overload.ewma_s,
                    threshold_s=self._overload.threshold_s)
        if len(self._queue) >= self.config.max_queue:
            if self.config.queue_policy == "drop-oldest":
                victim = self._queue.pop(0)
                self._terminate(victim, "shed")
            else:
                self.stats["rejected"] += 1
                self._count("rejected")
                raise ServerOverloaded(
                    "queue_full", queue_depth=len(self._queue))
        st = _State(request, now)
        self._queue.append(st)
        self.stats["submitted"] += 1
        self._count("submitted")
        self._publish_gauges()
        return st

    def _overload_transition(self, transition: str) -> None:
        reg = get_registry()
        reg.gauge("recsys_overload",
                  "1 while the recsys queue-delay overload detector is "
                  "tripped (new submits are shed)").set(
            float(transition == "enter"))
        reg.counter("recsys_overload_transitions_total",
                    "recsys overload detector state changes").inc(
            state=transition)

    # -- the serving iteration ----------------------------------------------
    def step(self) -> bool:
        """One iteration: expire stale queued requests, rank one batch.
        Returns whether work remains."""
        now = self.clock()
        self.stats["steps"] += 1
        keep: List[_State] = []
        for st in self._queue:
            if st.deadline_t is not None and now >= st.deadline_t:
                # expire BEFORE any row is fetched: a blown deadline
                # must not spend table bandwidth
                self._terminate(st, "expired")
            else:
                keep.append(st)
        self._queue = keep
        if self._overload is not None:
            delay = (now - self._queue[0].submitted_t
                     if self._queue else 0.0)
            transition = self._overload.observe(delay)
            if transition is not None:
                self._overload_transition(transition)
        batch = self._queue[:self.config.max_batch]
        self._queue = self._queue[len(batch):]
        if batch:
            self._rank(batch)
        self._publish_gauges()
        if self.config.publish_tier_metrics:
            for t in {id(t): t for e in getattr(self.model, "embeddings",
                                                [])
                      for t in [e.table]}.values():
                pub = getattr(t, "publish_tier_metrics", None)
                if pub is not None:
                    pub()
        return bool(self._queue)

    def _forward(self, dense: np.ndarray, ids: np.ndarray) -> np.ndarray:
        from ..core.tensor import no_grad
        with no_grad():
            return np.asarray(self.model(dense, ids)._data)

    def _observe_phase(self) -> None:
        reg = get_registry()
        tm = getattr(self.model, "last_timings", {})
        look, rank = tm.get("lookup_s", 0.0), tm.get("mlp_s", 0.0)
        self._lat["lookup"].append(look)
        self._lat["rank"].append(rank)
        reg.histogram("recsys_lookup_seconds",
                      "embedding lookup wall time per ranking batch"
                      ).observe(look)
        reg.histogram("recsys_rank_seconds",
                      "MLP + interaction wall time per ranking batch"
                      ).observe(rank)

    def _complete(self, st: _State, scores: np.ndarray,
                  now: float) -> None:
        reg = get_registry()
        order = np.argsort(-scores, kind="stable")
        e2e = now - st.submitted_t
        st.result = RecsysResult(st.request.request_id,
                                 scores.copy(), order, e2e_s=e2e)
        self._terminate(st, "completed")
        self.stats["candidates_scored"] += scores.size
        self._lat["e2e"].append(e2e)
        reg.histogram("recsys_e2e_seconds",
                      "submit -> ranked-results latency").observe(e2e)
        if st.deadline_t is not None:
            reg.histogram(
                "recsys_deadline_slack_seconds",
                "deadline minus completion time (negative = ranked "
                "late, only possible within one engine step)",
                buckets=(-1.0, -0.1, 0.0, 0.05, 0.1, 0.25, 0.5,
                         1.0, 2.0, 5.0, 30.0)).observe(
                st.deadline_t - now)
        if st.request.on_result is not None:
            st.request.on_result(st.result)

    @staticmethod
    def _dense_rows(st: _State, k: int) -> np.ndarray:
        return np.broadcast_to(
            np.asarray(st.request.dense, np.float32),
            (k, len(st.request.dense)))

    def _rank(self, batch: List[_State]) -> None:
        if self._t_first is None:
            self._t_first = self.clock()
        sizes = [int(st.request.candidate_ids.shape[0]) for st in batch]
        dense = np.concatenate([self._dense_rows(st, k)
                                for st, k in zip(batch, sizes)])
        ids = np.concatenate([np.asarray(st.request.candidate_ids,
                                         np.int64) for st in batch])
        try:
            logits = self._forward(dense, ids)
        except Exception:
            # fault isolation: one poisoned request (bad ids, a raising
            # model) must fail ALONE — re-run each request solo so its
            # batch-mates still complete and every request lands a
            # terminal outcome (the PR 8 per-slot discipline)
            self._rank_isolated(batch)
            return
        now = self.clock()
        self._t_last = now
        self._observe_phase()
        off = 0
        for st, k in zip(batch, sizes):
            self._complete(st, logits[off:off + k], now)
            off += k

    def _rank_isolated(self, batch: List[_State]) -> None:
        for st in batch:
            k = int(st.request.candidate_ids.shape[0])
            try:
                logits = self._forward(
                    self._dense_rows(st, k),
                    np.asarray(st.request.candidate_ids, np.int64))
            except Exception as e:
                st.failure = repr(e)
                self._terminate(st, "failed")
                continue
            now = self.clock()
            self._t_last = now
            self._observe_phase()
            self._complete(st, logits, now)

    def run(self, max_steps: Optional[int] = None) -> None:
        steps = 0
        while self._queue:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return

    # -- observability -------------------------------------------------------
    def metrics_summary(self) -> dict:
        def pct(xs, q):
            return float(np.percentile(np.asarray(xs), q)) if xs else None

        elapsed = (max(self._t_last - self._t_first, 1e-9)
                   if self._t_first is not None and self._t_last is not None
                   else None)
        return {
            "requests_submitted": self.stats["submitted"],
            "requests_completed": self.stats["completed"],
            "requests_expired": self.stats["expired"],
            "requests_rejected": self.stats["rejected"],
            "requests_shed": self.stats["shed"],
            "requests_failed": self.stats["failed"],
            "candidates_scored": self.stats["candidates_scored"],
            "elapsed_s": elapsed,
            "candidates_per_sec": (self.stats["candidates_scored"]
                                   / elapsed if elapsed else None),
            "e2e_p50_s": pct(self._lat["e2e"], 50),
            "e2e_p99_s": pct(self._lat["e2e"], 99),
            "lookup_p50_s": pct(self._lat["lookup"], 50),
            "lookup_p99_s": pct(self._lat["lookup"], 99),
        }


def reset_engines() -> None:
    """Test isolation: drop queued work from live engines and restart
    the request-id stream."""
    global _req_ids
    for eng in list(_LIVE_ENGINES):
        eng._queue.clear()
    _req_ids = itertools.count(1)
