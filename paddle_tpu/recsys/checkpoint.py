"""Sharded/tiered-table save & restore through the PR 5 atomic
checkpoint manifest.

Every save stages per-table ``.npz`` files into ``tables_<n>.tmp`` and
commits through :func:`~paddle_tpu.distributed.checkpoint._commit`
(fsync'd manifest with per-file sizes, atomic rename) — so a torn
write racing the commit (chaos site ``ckpt.write.torn``) is caught by
manifest verification and :func:`load_tables` falls back to the newest
VALID snapshot with a ``checkpoint_fallback`` flight event, exactly
like TrainStep checkpoints and drain snapshots. Table state is stored
in GLOBAL row order (``state_dict`` contracts), so a snapshot written
on one mesh layout restores onto another.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("paddle_tpu.recsys")

__all__ = ["save_tables", "load_tables", "latest_valid_snapshot"]

_SNAP_RE = re.compile(r"^tables_(\d+)$")
STATE_NAME = "recsys_tables.json"


def _seq(name: str) -> int:
    m = _SNAP_RE.match(name)
    return int(m.group(1)) if m else 0


def save_tables(root: str, tables: Dict[str, object],
                step: Optional[int] = None) -> str:
    """Commit ``{name: table}`` state as ``<root>/tables_<n>``; returns
    the committed path. ``step`` defaults to the next sequence number."""
    from ..distributed.checkpoint import STAGING_SUFFIX, _commit
    root = os.path.abspath(root)
    os.makedirs(root, exist_ok=True)
    n = (int(step) if step is not None
         else max((_seq(d) for d in os.listdir(root)), default=0) + 1)
    final = os.path.join(root, f"tables_{n}")
    tmp = final + STAGING_SUFFIX
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    doc = {"format": 1, "created": time.time(), "tables": {}}
    for name, table in tables.items():
        fname = f"{name}.npz"
        state = table.state_dict()
        np.savez(os.path.join(tmp, fname), **state)
        doc["tables"][name] = {"file": fname,
                               "keys": sorted(state)}
    _commit(tmp, final, leaves={},
            extra_files={STATE_NAME: json.dumps(doc, indent=1)}, step=n)
    return final


def latest_valid_snapshot(root: str) -> Tuple[Optional[str], List[int]]:
    """(newest valid snapshot path or None, skipped step numbers).
    Torn/uncommitted dirs are skipped with a ``checkpoint_fallback``
    flight event — the checkpoint-reader discipline."""
    from ..distributed.checkpoint import verify_checkpoint
    from ..monitor.flight_recorder import safe_record_event
    skipped: List[int] = []
    if not os.path.isdir(root):
        return None, skipped
    seqs = sorted((_seq(d) for d in os.listdir(root)
                   if _SNAP_RE.match(d)), reverse=True)
    for n in seqs:
        path = os.path.join(root, f"tables_{n}")
        reason = verify_checkpoint(path)
        if reason is None:
            return path, skipped
        logger.warning("recsys table restore: skipping %s: %s",
                       path, reason)
        safe_record_event("checkpoint_fallback", step=n, reason=reason,
                          kind="recsys_tables")
        skipped.append(n)
    return None, skipped


def load_tables(root: str, tables: Dict[str, object]) -> Optional[str]:
    """Restore ``{name: table}`` from the newest valid snapshot under
    ``root`` (falling back past torn commits). Returns the snapshot
    path, or None when no valid snapshot exists (tables untouched)."""
    path, _skipped = latest_valid_snapshot(root)
    if path is None:
        return None
    with open(os.path.join(path, STATE_NAME)) as f:
        doc = json.load(f)
    for name, table in tables.items():
        entry = (doc.get("tables") or {}).get(name)
        if entry is None:
            raise KeyError(
                f"snapshot {path} has no table {name!r} "
                f"(has: {sorted(doc.get('tables') or {})})")
        with np.load(os.path.join(path, entry["file"])) as z:
            table.load_state_dict({k: z[k] for k in z.files})
    return path
