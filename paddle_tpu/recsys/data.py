"""Seeded criteo-shaped synthetic workload (power-law sparse ids).

Methodology (docs/RECSYS.md): the criteo click-logs shape is
``num_dense`` float features + ``num_sparse`` categorical slots with
one id each, labels ~1 bit. Real criteo id traffic is power-law — a
handful of hot ids dominate every batch (that skew is WHY dedup lookups
and hot-ID tiering pay off) — so ids here draw from a bounded zipf:
``P(rank r) ∝ 1/(r+1)^alpha`` over each slot's vocab, rank == id (hot
ids are the small ids; deterministic, so tests can target the hot set
by construction).

Labels come from a planted logistic teacher (a fixed random linear
model over the dense features plus a per-(slot, id-bucket) embedding
score), so DLRM training has real signal to descend — the bench's
examples/s is measured on a learnable task, not noise.

Everything is seeded and batch-indexed: ``batch(i)`` is a pure function
of ``(seed, i)``, so two readers of the same config see byte-identical
streams (loadgen discipline).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple, Union

import numpy as np

__all__ = ["CriteoSynthetic"]


class CriteoSynthetic:
    """Deterministic DLRM workload generator.

    ``vocab_sizes`` is one int (shared by every sparse slot) or a
    per-slot list. ``alpha`` is the zipf exponent (≈1.05 matches
    published criteo fits; higher = hotter head).
    """

    def __init__(self, num_dense: int = 4, num_sparse: int = 8,
                 vocab_sizes: Union[int, Sequence[int]] = 10_000,
                 alpha: float = 1.05, batch_size: int = 128,
                 seed: int = 0, teacher_buckets: int = 1024):
        self.num_dense = int(num_dense)
        self.num_sparse = int(num_sparse)
        if isinstance(vocab_sizes, (int, np.integer)):
            vocab_sizes = [int(vocab_sizes)] * self.num_sparse
        if len(vocab_sizes) != self.num_sparse:
            raise ValueError("vocab_sizes must match num_sparse")
        self.vocab_sizes: List[int] = [int(v) for v in vocab_sizes]
        self.alpha = float(alpha)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        # bounded-zipf inverse CDF per slot (float64 for a stable
        # cumsum; vocabs are bounded so the table is explicit)
        self._cdfs = []
        for v in self.vocab_sizes:
            w = 1.0 / np.power(np.arange(1, v + 1, dtype=np.float64),
                               self.alpha)
            self._cdfs.append(np.cumsum(w / w.sum()))
        # planted teacher: dense weights + per-(slot, id-bucket) scores
        trng = np.random.default_rng(self.seed ^ 0x7EC5)
        self._w_dense = trng.normal(0.0, 1.0, (self.num_dense,)) \
            .astype(np.float32)
        self._buckets = int(teacher_buckets)
        self._w_sparse = trng.normal(
            0.0, 1.0, (self.num_sparse, self._buckets)).astype(np.float32)

    def sample_ids(self, rng: np.random.Generator,
                   n: int) -> np.ndarray:
        """``[n, num_sparse]`` bounded-zipf draws — the ONE sampling
        rule, shared by :meth:`batch` and external candidate
        generators (the serving bench draws ranking candidates from
        the same distribution the tables were trained on)."""
        ids = np.empty((n, self.num_sparse), np.int64)
        for f, cdf in enumerate(self._cdfs):
            ids[:, f] = np.searchsorted(cdf, rng.random(n))
        return ids

    def batch(self, i: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Batch ``i`` → (dense [B, num_dense] f32, ids [B, num_sparse]
        i64, labels [B] f32) — a pure function of (seed, i)."""
        rng = np.random.default_rng((self.seed << 20) + int(i))
        B = self.batch_size
        dense = rng.normal(0.0, 1.0, (B, self.num_dense)) \
            .astype(np.float32)
        ids = self.sample_ids(rng, B)
        logit = dense @ self._w_dense
        for f in range(self.num_sparse):
            logit = logit + self._w_sparse[f, ids[:, f] % self._buckets] \
                / np.sqrt(self.num_sparse)
        prob = 1.0 / (1.0 + np.exp(-logit))
        labels = (rng.random(B) < prob).astype(np.float32)
        return dense, ids, labels

    def batches(self, steps: int, start: int = 0):
        for i in range(start, start + int(steps)):
            yield self.batch(i)
