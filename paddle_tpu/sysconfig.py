"""paddle.sysconfig (reference: python/paddle/sysconfig.py)."""

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(__file__)


def get_include() -> str:
    return os.path.join(_ROOT, "include")


def get_lib() -> str:
    return os.path.join(_ROOT, "libs")
