"""Deterministic chaos injection: named fault sites the recovery paths
are proven against.

Production fault tolerance that has never seen a fault is a guess. This
module puts *named probe sites* on the framework's recovery-relevant
code paths; a test (or ``bench.py --chaos``) arms a subset of them with
a deterministic plan, and the site fires exactly where and when the plan
says — so every recovery path (torn-checkpoint fallback, collective
timeout, skip-and-continue, elastic restart) is exercised reproducibly
instead of waiting for production to exercise it for you.

Built-in sites (``register_site`` adds more):

- ``ckpt.write.torn``       truncate a checkpoint data file AFTER its
                            manifest checksum was recorded (a torn write
                            racing the commit) — verification must catch
                            it and ``latest_step`` must fall back.
- ``ckpt.manifest.corrupt`` scribble over the committed manifest — the
                            directory must read as invalid, never as an
                            empty-but-plausible checkpoint.
- ``collective.hang``       an eager collective dispatch blocks (bounded
                            sleep, cancellable) — the
                            ``FLAGS_collective_timeout_s`` watchdog must
                            convert it into ``CollectiveTimeoutError``.
- ``grad.nonfinite``        the TrainStep loss comes back NaN — the
                            ``skip_nonfinite_budget`` policy must skip
                            the update and continue.
- ``worker.die``            the training process dies at a step boundary
                            (raises :class:`ChaosFault` from
                            ``CheckpointManager.on_step``) — elastic
                            restart must resume from the last commit.

Plans are armed via :func:`configure` with a spec string (also read from
``FLAGS_chaos`` / ``FLAGS_chaos_seed`` on first probe), or
programmatically via :func:`arm`:

    site            fire on every occurrence
    site@N          fire on the N-th occurrence (1-based) only
    site:p          fire with probability p per occurrence —
                    deterministic in (seed, site, occurrence)
    ...*k           cap total fires at k

``probe(site)`` is the hook the framework calls: it counts the
occurrence and answers "does the fault fire here, now?". Disarmed
(default), :func:`active` is a single cached-bool check — the probe
sites cost nothing in production. Every fire lands in the
flight-recorder event log (when recording is enabled) so chaos runs
leave the same forensics a real fault would.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["SITES", "ChaosFault", "register_site", "configure", "arm",
           "active", "probe", "fired", "occurrences", "reset",
           "cancel_hangs", "rearm_hangs", "hang_loop", "chaos_scope"]

# site -> one-line description (the registry doubles as typo protection:
# arming or probing an unknown site is a bug in the caller, not a fault)
SITES: Dict[str, str] = {
    "ckpt.write.torn": "truncate a checkpoint file after its checksum "
                       "was recorded, before the commit rename",
    "ckpt.manifest.corrupt": "scribble over the committed checkpoint "
                             "manifest",
    "collective.hang": "block an eager collective dispatch (bounded, "
                       "cancellable sleep)",
    "grad.nonfinite": "replace the TrainStep loss with NaN",
    "worker.die": "kill the training loop at a step boundary",
    # serving sites (ISSUE 8; probed by paddle_tpu.serving — built in so
    # `bench.py --chaos` can arm them before the serving import)
    "serve.decode.hang": "block a serving decode dispatch (bounded, "
                         "cancellable sleep) — the FLAGS_serve_watchdog_s "
                         "watchdog must convert it into "
                         "DecodeWatchdogError",
    "serve.request.poison": "poison a submitted request: its sampled "
                            "logits row turns non-finite, so fault "
                            "isolation must fail ONLY that slot",
    "serve.pages.exhaust": "pretend the KV page pool ran dry for one "
                           "scheduler decision: admission waits / the "
                           "newest-admitted request is recompute-"
                           "preempted",
    "serve.detok.raise": "raise from the streaming detokenizer/on_token "
                         "callback of one accepted token",
    # model-lifecycle sites (ISSUE 20; probed by serving/engine.py +
    # serving/lifecycle.py — built in so `bench.py --chaos` can arm
    # them before the serving import)
    "serve.swap.torn_manifest": "a candidate weight push reads as torn "
                                "at verification time: swap_weights "
                                "must refuse it and the OLD weights "
                                "keep serving",
    "serve.swap.bad_weights": "plant non-finite values into a loaded "
                              "candidate param tree AFTER verification "
                              "(the corruption manifests as NaN logits "
                              "in flight — the auto-rollback drill)",
    "serve.swap.replica_die_mid_swap": "the candidate replica dies "
                                       "while its swap is staged: the "
                                       "lifecycle controller must "
                                       "abort, migrate its in-flight "
                                       "work and leave the baseline "
                                       "untouched",
}


class ChaosFault(RuntimeError):
    """An injected fault that models sudden process death (site
    ``worker.die``); carries the site name for supervisors that want to
    distinguish injected faults from organic ones."""

    def __init__(self, site: str, message: Optional[str] = None):
        super().__init__(message or f"chaos-injected fault at {site!r}")
        self.site = site


def register_site(name: str, description: str = "") -> None:
    """Declare an additional probe site (idempotent)."""
    SITES.setdefault(name, description)


class _Plan:
    __slots__ = ("at", "prob", "times", "fires")

    def __init__(self, at: Optional[int] = None,
                 prob: Optional[float] = None,
                 times: Optional[int] = None):
        if at is not None and at < 1:
            raise ValueError("chaos: @N occurrence index is 1-based")
        if prob is not None and not (0.0 <= prob <= 1.0):
            raise ValueError(f"chaos: probability {prob} outside [0, 1]")
        self.at = at
        self.prob = prob
        # an @N plan is a single shot unless *k says otherwise
        self.times = times if times is not None else (1 if at is not None
                                                      else None)
        self.fires = 0


class ChaosInjector:
    """One process-wide injector; tests swap/inspect it via the module
    functions. All decisions are host-side and deterministic."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self._plans: Dict[str, _Plan] = {}
            self._counts: Dict[str, int] = {}
            self._fired: List[Tuple[str, int]] = []
            self._seed = 0
            self._armed = False
            self._flags_checked = False
            # cancels in-flight hang_loop sleeps so a chaos-hung worker
            # thread exits promptly at test teardown
            self._cancel = threading.Event()

    # -- arming ------------------------------------------------------------
    def configure(self, spec: str, seed: int = 0) -> None:
        """Parse a ``site[@N|:p][*k]`` comma list and arm those plans
        (replacing any current plans)."""
        self.reset()
        self._seed = int(seed)
        self._flags_checked = True
        for raw in (spec or "").split(","):
            entry = raw.strip()
            if not entry:
                continue
            times = None
            if "*" in entry:
                entry, times_s = entry.rsplit("*", 1)
                times = int(times_s)
            at = prob = None
            if "@" in entry:
                entry, at_s = entry.split("@", 1)
                at = int(at_s)
            elif ":" in entry:
                entry, prob_s = entry.rsplit(":", 1)
                prob = float(prob_s)
            self.arm(entry.strip(), at=at, prob=prob, times=times)

    def arm(self, site: str, at: Optional[int] = None,
            prob: Optional[float] = None,
            times: Optional[int] = None) -> None:
        if site not in SITES:
            raise ValueError(
                f"chaos: unknown site {site!r}; known sites: "
                f"{', '.join(sorted(SITES))} (register_site adds more)")
        with self._lock:
            self._plans[site] = _Plan(at=at, prob=prob, times=times)
            self._armed = True
            self._flags_checked = True

    def _load_flags(self) -> None:
        """Pick up FLAGS_chaos / FLAGS_chaos_seed once (first probe)."""
        self._flags_checked = True
        try:
            from ..core.flags import get_flag
            spec = get_flag("chaos")
            seed = int(get_flag("chaos_seed"))
        except Exception:
            return
        if spec:
            self.configure(spec, seed=seed)

    # -- probing -----------------------------------------------------------
    def active(self) -> bool:
        if not self._flags_checked:
            self._load_flags()
        return self._armed

    def probe(self, site: str) -> bool:
        """Count one occurrence of ``site`` and decide whether the armed
        plan fires here. False (and no counting) when disarmed."""
        if not self.active():
            return False
        with self._lock:
            plan = self._plans.get(site)
            if plan is None:
                return False
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            if plan.times is not None and plan.fires >= plan.times:
                return False
            if plan.at is not None:
                fire = n == plan.at
            elif plan.prob is not None:
                fire = random.Random(
                    f"{self._seed}:{site}:{n}").random() < plan.prob
            else:
                fire = True
            if not fire:
                return False
            plan.fires += 1
            self._fired.append((site, n))
        # forensics: a chaos fire is an event a post-mortem must see
        # next to the recovery it triggered
        try:
            from ..monitor import flight_recorder as _flight
            if _flight.enabled():
                _flight.get_flight_recorder().record_event(
                    "chaos", site=site, occurrence=n)
        except Exception:
            pass
        return True

    def hang_loop(self, max_s: float = 60.0) -> None:
        """Cancellable bounded block (site ``collective.hang``): sleeps
        until :meth:`reset` cancels it or ``max_s`` elapses, so a hung
        worker thread never outlives the test that armed it."""
        cancel = self._cancel
        deadline = time.monotonic() + max_s
        while not cancel.is_set() and time.monotonic() < deadline:
            cancel.wait(0.05)


_state = ChaosInjector()


def configure(spec: str, seed: int = 0) -> None:
    _state.configure(spec, seed=seed)


def arm(site: str, at: Optional[int] = None, prob: Optional[float] = None,
        times: Optional[int] = None) -> None:
    _state.arm(site, at=at, prob=prob, times=times)


def active() -> bool:
    """Whether any site is armed (cheap: the hot-path guard)."""
    return _state.active()


def probe(site: str) -> bool:
    return _state.probe(site)


def fired() -> List[Tuple[str, int]]:
    """(site, occurrence) pairs that fired, in order."""
    return list(_state._fired)


def occurrences(site: str) -> int:
    """How many times ``site`` was probed while armed."""
    return _state._counts.get(site, 0)


def cancel_hangs() -> None:
    """Cancel in-flight :func:`hang_loop` sleeps WITHOUT disarming the
    plans (engine/watchdog teardown: abandoned hung worker threads must
    exit promptly even before the test-scope chaos reset runs).
    Subsequent hangs in this arming no-op until :func:`reset` or
    :func:`rearm_hangs`."""
    _state._cancel.set()


def rearm_hangs() -> None:
    """Re-enable hang sites after :func:`cancel_hangs` (one engine's
    shutdown must not neutralize still-armed chaos for other live
    engines). Threads blocked on the old cancel event still exit; new
    :func:`hang_loop` calls honour fresh cancels."""
    _state._cancel = threading.Event()


def reset() -> None:
    """Disarm everything and cancel in-flight hangs (test teardown)."""
    _state._cancel.set()
    _state.reset()
    # reset() marks flags as checked: a FLAGS_chaos value armed for one
    # test must not silently resurrect in the next
    _state._flags_checked = True


def hang_loop(max_s: float = 60.0) -> None:
    _state.hang_loop(max_s)


class chaos_scope:
    """``with chaos_scope("grad.nonfinite@2"):`` — configure on entry,
    reset on exit (the test-local arming idiom)."""

    def __init__(self, spec: str, seed: int = 0):
        self._spec, self._seed = spec, seed

    def __enter__(self):
        configure(self._spec, seed=self._seed)
        return _state

    def __exit__(self, *exc):
        reset()
        return False
