"""Test-support utilities shipped with the framework.

``paddle_tpu.testing.chaos`` is the deterministic fault injector the
fault-tolerance stack (atomic checkpoints, collective timeouts,
skip-and-continue) is proven against — see docs/FAULT_TOLERANCE.md.
"""

from . import chaos  # noqa: F401

__all__ = ["chaos"]
