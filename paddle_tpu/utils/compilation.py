"""Compilation observability: compile / trace-count counters.

CI analogue of the reference's op-benchmark gate for COMPILE cost: the
scan-over-layers work (nn/scan.py) makes trace+compile O(1) in stack depth,
and this module gives tests a way to PIN that property so a layer-loop
re-trace can't silently regress it.

Counts come from two sources:
- jax's monitoring events (``/jax/core/compile/backend_compile_duration``
  fires once per XLA backend compile; ``/jax/compilation_cache/
  cache_misses`` fires when the persistent compilation cache misses —
  jax.monitoring has no unregister, so one process-wide listener feeds
  monotonic counters and :class:`CompileCounter` diffs snapshots);
- nn.scan's Python-level body-trace counter (``SCAN_STATS``), which is
  backend-independent and exact.

Usage::

    with CompileCounter() as c:
        step(ids, labels)           # cold: traces + compiles
    assert c.scan_body_traces <= 2  # one fwd trace (+1 remat), not O(L)
    with CompileCounter() as c:
        step(ids, labels)           # warm: cached executable
    assert c.backend_compiles == 0
"""

from __future__ import annotations

import threading

import jax

__all__ = ["CompileCounter", "compile_counts", "publish_compile_counts"]

_LOCK = threading.Lock()
_COUNTS = {"backend_compiles": 0, "cache_misses": 0, "jaxpr_traces": 0}
_installed = False


def _on_duration(event: str, duration: float, **kwargs) -> None:
    with _LOCK:
        if event == "/jax/core/compile/backend_compile_duration":
            _COUNTS["backend_compiles"] += 1
        elif event == "/jax/core/compile/jaxpr_trace_duration":
            _COUNTS["jaxpr_traces"] += 1


def _on_event(event: str, **kwargs) -> None:
    with _LOCK:
        if event == "/jax/compilation_cache/cache_misses":
            _COUNTS["cache_misses"] += 1


def _install() -> None:
    global _installed
    with _LOCK:
        if _installed:
            return
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        jax.monitoring.register_event_listener(_on_event)
        _installed = True


def compile_counts() -> dict:
    """Process-lifetime monotonic counters (installs listeners on first
    use; counting starts then)."""
    _install()
    with _LOCK:
        return dict(_COUNTS)


def publish_compile_counts(registry=None) -> dict:
    """Bridge the process-lifetime compile counters into the monitor
    metrics registry as gauges (``jax_backend_compiles``,
    ``jax_cache_misses``, ``jax_jaxpr_traces``, plus nn.scan's
    ``scan_body_traces``/``scan_calls``) — called by bench.py before its
    JSONL dump so perf records carry recompile counts. Returns the raw
    counts dict."""
    counts = compile_counts()
    try:
        from ..nn.scan import SCAN_STATS
        counts = dict(counts, scan_body_traces=SCAN_STATS["body_traces"],
                      scan_calls=SCAN_STATS["scan_calls"])
    except Exception:
        pass
    from ..monitor import get_registry
    reg = registry if registry is not None else get_registry()
    for k, v in counts.items():
        name = k if k.startswith("scan_") else "jax_" + k
        # emits-metrics: jax_backend_compiles, jax_cache_misses,
        # emits-metrics: jax_jaxpr_traces, scan_body_traces, scan_calls
        reg.gauge(name, "process-lifetime compile/trace counter "
                        "(utils.compilation)").set(v)
    return counts


class CompileCounter:
    """Context manager: compile/trace activity within the block.

    Attributes after (or during) the block:
    - ``backend_compiles``: XLA backend compiles started in the block
    - ``cache_misses``: persistent compilation-cache misses
    - ``jaxpr_traces``: jaxpr traces (every jit signature traces >= once)
    - ``scan_body_traces`` / ``scan_calls``: nn.scan body traces — the
      "one trace per stack, not per layer" pin
    """

    def __enter__(self):
        from ..nn.scan import SCAN_STATS
        _install()
        self._scan_stats = SCAN_STATS
        with _LOCK:
            self._snap = dict(_COUNTS)
        self._scan_snap = dict(SCAN_STATS)
        return self

    def __exit__(self, *exc):
        return False

    def _delta(self, key: str) -> int:
        with _LOCK:
            return _COUNTS[key] - self._snap[key]

    @property
    def backend_compiles(self) -> int:
        return self._delta("backend_compiles")

    @property
    def cache_misses(self) -> int:
        return self._delta("cache_misses")

    @property
    def jaxpr_traces(self) -> int:
        return self._delta("jaxpr_traces")

    @property
    def scan_body_traces(self) -> int:
        return self._scan_stats["body_traces"] - self._scan_snap["body_traces"]

    @property
    def scan_calls(self) -> int:
        return self._scan_stats["scan_calls"] - self._scan_snap["scan_calls"]
