"""Custom-operator extension: C++ host ops + python-level op registration.

reference parity: python/paddle/utils/cpp_extension/cpp_extension.py:51
(setup/load compiling user C++ into loadable ops) and the PD_BUILD_OP
macro story (extension/include/ext_op_meta_info.h:501; example
tests/custom_op/custom_relu_op.cc).

TPU-native redesign: the accelerator compute path for custom kernels is
Pallas (`register_op` takes any jnp/pallas callable + optional VJP and
returns a tape-aware Tensor op — no C++ needed for device code). C++
remains first-class for HOST ops (pre/post-processing, lookups): `load`
compiles the source with g++ into a shared library and binds exported
symbols through `jax.pure_callback`, so the op works inside jit (the
callback runs host-side, XLA streams the data — the TPU analogue of the
reference's CPU custom kernels).

C symbol convention (the reference example shape, custom_relu_op.cc):
    void <name>(const float* x, float* y, int64_t n);            // fwd
    void <name>_grad(const float* x, const float* gy,
                     float* gx, int64_t n);                      // bwd
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, _is_tracer, apply

__all__ = ["register_op", "load", "CppExtension"]


def register_op(name: str, fn: Callable, vjp: Optional[Callable] = None):
    """Register a python/Pallas custom operator.

    fn(*arrays) -> array; vjp(primals, cotangent) -> tuple of input
    cotangents. Returns a callable over Tensors that participates in
    eager autograd and jit (the analogue of PD_BUILD_OP +
    PD_BUILD_GRAD_OP).
    """
    if vjp is not None:
        @jax.custom_vjp
        def core(*args):
            return fn(*args)

        def fwd(*args):
            return fn(*args), args

        def bwd(res, g):
            out = vjp(res, g)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        core.defvjp(fwd, bwd)
    else:
        core = fn

    def op(*tensors):
        ts = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
              for t in tensors]
        return apply(core, *ts, name=name)

    op.__name__ = name
    return op


class CppExtension:
    """A compiled host-op library; exported symbols become Tensor ops."""

    def __init__(self, lib_path: str, functions: Sequence[str]):
        self._lib = ctypes.CDLL(lib_path)
        self.lib_path = lib_path
        for fname in functions:
            setattr(self, fname, self._bind(fname))

    def _c_fn(self, symbol):
        f = getattr(self._lib, symbol)
        f.restype = None
        return f

    def _bind(self, fname: str):
        fwd_c = self._c_fn(fname)
        try:
            grad_c = self._c_fn(fname + "_grad")
        except AttributeError:
            grad_c = None

        def host_fwd(x):
            x = np.ascontiguousarray(x, np.float32)
            y = np.empty_like(x)
            fwd_c(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  ctypes.c_int64(x.size))
            return y

        def host_bwd(x, gy):
            x = np.ascontiguousarray(x, np.float32)
            gy = np.ascontiguousarray(gy, np.float32)
            gx = np.empty_like(x)
            grad_c(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   gy.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   gx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   ctypes.c_int64(x.size))
            return gx

        def fwd_arr(x):
            return jax.pure_callback(
                host_fwd, jax.ShapeDtypeStruct(x.shape, jnp.float32), x)

        def vjp_arr(primals, g):
            (x,) = primals
            gx = jax.pure_callback(
                host_bwd, jax.ShapeDtypeStruct(x.shape, jnp.float32), x, g)
            return (gx,)

        traced_op = (register_op(fname, fwd_arr) if grad_c is None
                     else register_op(fname, fwd_arr, vjp_arr))

        def op(x):
            from ..core.tensor import (TapeNode, Tensor as T,
                                       _wrap_outputs, is_grad_enabled)
            t = x if isinstance(x, T) else T(jnp.asarray(x))
            if _is_tracer(t._data):
                # under jit: route through pure_callback (host callbacks —
                # available on real TPU runtimes)
                return traced_op(t)
            # eager: run the C function directly on a host copy; the tape
            # node calls the _grad symbol directly too — no jax host
            # callback machinery involved
            x_np = np.asarray(t._data)
            out = jnp.asarray(host_fwd(x_np))
            node = None
            if grad_c is not None and is_grad_enabled() \
                    and not t.stop_gradient:
                def vjp_fn(g, x_np=x_np):
                    return (jnp.asarray(host_bwd(x_np, np.asarray(g))),)
                node = TapeNode(vjp_fn, [t],
                                [jax.ShapeDtypeStruct(out.shape, out.dtype)],
                                name=fname)
            return _wrap_outputs(out, node=node)

        op.__name__ = fname
        return op


def load(name: str, sources: Sequence[str], functions: Sequence[str],
         extra_cxx_cflags: Sequence[str] = (),
         build_directory: Optional[str] = None,
         verbose: bool = False) -> CppExtension:
    """Compile C++ sources into a host-op extension (reference:
    cpp_extension.load — JIT build via setuptools; here a direct g++
    -shared build, no setuptools round trip)."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    lib_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    newest = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < newest:
        cmd = ["g++", "-O2", "-shared", "-fPIC", *extra_cxx_cflags,
               *srcs, "-o", lib_path]
        if verbose:
            print(" ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"building extension {name!r} failed:\n{proc.stderr}")
    return CppExtension(lib_path, functions)
