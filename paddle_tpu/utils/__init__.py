"""paddle.utils namespace (reference parity: python/paddle/utils)."""

from . import compilation  # noqa: F401
from . import cpp_extension  # noqa: F401
from .compilation import CompileCounter  # noqa: F401
