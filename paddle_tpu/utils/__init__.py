"""paddle.utils namespace (reference parity: python/paddle/utils)."""

from . import cpp_extension  # noqa: F401
