"""paddle.hub: load models/entrypoints from a hubconf.py.

reference parity: python/paddle/hub.py — list/help/load over github/gitee
/local sources. This environment has no egress, so remote sources raise
with a clear message; the LOCAL source (a directory containing
hubconf.py, the dominant intra-org use) is fully supported.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} under {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _resolve(repo_dir: str, source: str):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access, which this "
            "environment does not have; clone the repo and use "
            "source='local'")
    return _load_hubconf(repo_dir)


def list(repo_dir: str, source: str = "local", force_reload: bool = False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf."""
    mod = _resolve(repo_dir, source)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",  # noqa: A001
         force_reload: bool = False):
    mod = _resolve(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}")
    return fn.__doc__


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    """Instantiate entrypoint ``model`` from the repo's hubconf."""
    mod = _resolve(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"no callable entrypoint {model!r} in {repo_dir}")
    return fn(**kwargs)
