"""Profiler.

Reference: paddle/fluid/platform/profiler.h (host RecordEvent) +
device_tracer.cc (CUPTI timeline) + python fluid/profiler.py.

TPU answer: wrap jax.profiler (XPlane traces viewable in TensorBoard /
Perfetto) and keep a lightweight host-side event aggregation for op tables.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Optional

import jax

__all__ = ["Profiler", "RecordEvent", "profiler", "start_profiler",
           "stop_profiler", "summary", "profile_train_step",
           "export_chrome_tracing"]

_tls = threading.local()
_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_sec]
_active = [False]
# host timeline: (name, t_start_us, dur_us, thread_id); bounded so a long
# run cannot grow without limit (the chrome trace keeps the newest events)
_TIMELINE_CAP = 200_000
_timeline = []


def _timeline_add(name: str, t0: float, t1: float):
    if len(_timeline) >= _TIMELINE_CAP:
        del _timeline[: _TIMELINE_CAP // 2]
    _timeline.append((name, t0 * 1e6, (t1 - t0) * 1e6,
                      threading.get_ident()))


class RecordEvent:
    """Host-side RAII event marker (platform/profiler.h RecordEvent analogue);
    also emits a jax.profiler.TraceAnnotation so events appear on xplane."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def __enter__(self):
        self.t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if _active[0]:
            t1 = time.perf_counter()
            rec = _events[self.name]
            rec[0] += 1
            rec[1] += t1 - self.t0
            _timeline_add(self.name, self.t0, t1)
        return False


def _op_hook(name: str, seconds: float):
    rec = _events["op::" + name]
    rec[0] += 1
    rec[1] += seconds
    t1 = time.perf_counter()
    _timeline_add("op::" + name, t1 - seconds, t1)


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    """Begin host-event + per-op aggregation; with ``log_dir`` also start
    a jax.profiler XPlane trace there (view in TensorBoard/Perfetto —
    reference analogue: device_tracer.cc:464 timeline capture).

    Workflow::

        profiler.start_profiler(log_dir="/tmp/trace")
        ... train steps ...
        profiler.stop_profiler()
        print(profiler.summary())           # host events + eager op table
        # device timeline: tensorboard --logdir /tmp/trace
    """
    _active[0] = True
    _events.clear()
    _timeline.clear()
    from ..core.tensor import set_op_profile_hook
    set_op_profile_hook(_op_hook)
    if log_dir:
        jax.profiler.start_trace(log_dir)
        _tls.trace_dir = log_dir


def stop_profiler(sorted_key=None, profile_path=None):
    _active[0] = False
    from ..core.tensor import set_op_profile_hook
    set_op_profile_hook(None)
    if getattr(_tls, "trace_dir", None):
        jax.profiler.stop_trace()
        _tls.trace_dir = None


def summary(sorted_by="total"):
    rows = sorted(_events.items(), key=lambda kv: -kv[1][1])
    lines = [f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>12}"]
    for name, (count, total) in rows:
        lines.append(f"{name:<40} {count:>8} {total * 1e3:>12.3f} "
                     f"{total * 1e3 / max(count, 1):>12.3f}")
    return "\n".join(lines)


def export_chrome_tracing(path: str) -> str:
    """Write the host-side event timeline as a chrome trace
    (chrome://tracing / Perfetto JSON; the reference emits its
    profiler.proto timeline the same way, device_tracer.cc GenProfile:496).
    Device-side kernels live in the XPlane trace captured via
    ``start_profiler(log_dir=...)``; this file covers the host lanes
    (RecordEvent blocks + eager op dispatches)."""
    import json

    events = [{"name": name, "ph": "X", "ts": ts, "dur": dur,
               "pid": 0, "tid": tid % 100000, "cat": "host"}
              for name, ts, dur, tid in _timeline]
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return path


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default", log_dir=None,
             sorted_key="total"):
    """fluid.profiler.profiler context analogue."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler()
        print(summary(sorted_key))


def profile_train_step(step, batch, iters: int = 10, warmup: int = 2):
    """Attribute a TrainStep's wall time: compile vs host prep vs dispatch
    vs device execute (reference analogue: the per-op timeline totals of
    platform/profiler.cc, collapsed to the phases that exist under XLA's
    one-executable-per-step model).

    Returns a dict:
      compile_s       time of the first (cold) call incl. compilation;
                      ~0 when the persistent compile cache is warm
      host_ms         python-side prep per step (batch placement, flatten,
                      signature lookup) — measured by timing dispatch-only
                      calls minus the jitted dispatch itself
      dispatch_ms     time for step() to RETURN (async dispatch)
      step_ms         full step latency incl. device work (readback-timed)
      device_ms_est   step_ms minus host prep: device execute + dispatch
                      enqueue time (>= 0)
    """
    import numpy as np

    def readback(loss):
        return float(np.asarray(loss._data if hasattr(loss, "_data")
                                else loss))

    t0 = time.perf_counter()
    readback(step(*batch))
    compile_s = time.perf_counter() - t0

    for _ in range(warmup):
        step(*batch)
    readback(step(*batch))

    # host-side prep: everything __call__ does before the XLA dispatch
    t0 = time.perf_counter()
    for _ in range(iters):
        raw = [b._data if hasattr(b, "_data") else b for b in batch]
        raw = step._place_batch(raw)
        jax.tree_util.tree_flatten(raw)
    host_ms = (time.perf_counter() - t0) / iters * 1e3

    # dispatch: call returns as soon as XLA enqueues
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(*batch)
    dispatch_ms = (time.perf_counter() - t0) / iters * 1e3
    readback(loss)

    # full latency: readback forces device completion each step
    t0 = time.perf_counter()
    for _ in range(iters):
        readback(step(*batch))
    step_ms = (time.perf_counter() - t0) / iters * 1e3

    return {
        "compile_s": compile_s,
        "host_ms": host_ms,
        "dispatch_ms": dispatch_ms,
        "step_ms": step_ms,
        "device_ms_est": max(0.0, step_ms - host_ms),
    }


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir="./profiler_log"):
        self.log_dir = log_dir

    def start(self):
        jax.profiler.start_trace(self.log_dir)

    def stop(self):
        jax.profiler.stop_trace()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
