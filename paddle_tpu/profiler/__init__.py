"""Profiler.

Reference: paddle/fluid/platform/profiler.h (host RecordEvent) +
device_tracer.cc (CUPTI timeline) + python fluid/profiler.py.

TPU answer: wrap jax.profiler (XPlane traces viewable in TensorBoard /
Perfetto) and keep a lightweight host-side event aggregation for op tables.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Optional

import jax

__all__ = ["Profiler", "RecordEvent", "profiler", "start_profiler",
           "stop_profiler", "summary"]

_tls = threading.local()
_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_sec]
_active = [False]


class RecordEvent:
    """Host-side RAII event marker (platform/profiler.h RecordEvent analogue);
    also emits a jax.profiler.TraceAnnotation so events appear on xplane."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def __enter__(self):
        self.t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if _active[0]:
            rec = _events[self.name]
            rec[0] += 1
            rec[1] += time.perf_counter() - self.t0
        return False


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    _active[0] = True
    _events.clear()
    if log_dir:
        jax.profiler.start_trace(log_dir)
        _tls.trace_dir = log_dir


def stop_profiler(sorted_key=None, profile_path=None):
    _active[0] = False
    if getattr(_tls, "trace_dir", None):
        jax.profiler.stop_trace()
        _tls.trace_dir = None


def summary(sorted_by="total"):
    rows = sorted(_events.items(), key=lambda kv: -kv[1][1])
    lines = [f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>12}"]
    for name, (count, total) in rows:
        lines.append(f"{name:<40} {count:>8} {total * 1e3:>12.3f} "
                     f"{total * 1e3 / max(count, 1):>12.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default", log_dir=None,
             sorted_key="total"):
    """fluid.profiler.profiler context analogue."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler()
        print(summary(sorted_key))


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir="./profiler_log"):
        self.log_dir = log_dir

    def start(self):
        jax.profiler.start_trace(self.log_dir)

    def stop(self):
        jax.profiler.stop_trace()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
