"""Profiler.

Reference: paddle/fluid/platform/profiler.h (host RecordEvent) +
device_tracer.cc (CUPTI timeline) + python fluid/profiler.py.

TPU answer: wrap jax.profiler (XPlane traces viewable in TensorBoard /
Perfetto) and keep a lightweight host-side event aggregation for op tables.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Optional

import jax

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "profiler", "start_profiler", "stop_profiler",
           "summary", "profile_train_step", "export_chrome_tracing",
           "export_tensorboard", "chrome_trace_doc"]

_tls = threading.local()
_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_sec]
_active = [False]
# host timeline: (name, t_start_us, dur_us, thread_id); bounded so a long
# run cannot grow without limit (the chrome trace keeps the newest events)
_TIMELINE_CAP = 200_000
_timeline = []


def _timeline_add(name: str, t0: float, t1: float):
    if len(_timeline) >= _TIMELINE_CAP:
        del _timeline[: _TIMELINE_CAP // 2]
    _timeline.append((name, t0 * 1e6, (t1 - t0) * 1e6,
                      threading.get_ident()))


class RecordEvent:
    """Host-side RAII event marker (platform/profiler.h RecordEvent analogue);
    also emits a jax.profiler.TraceAnnotation so events appear on xplane."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None

    def __enter__(self):
        self.t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if _active[0]:
            t1 = time.perf_counter()
            rec = _events[self.name]
            rec[0] += 1
            rec[1] += t1 - self.t0
            _timeline_add(self.name, self.t0, t1)
        return False


def _op_hook(name: str, seconds: float):
    # bounded behind _active: the hook may still be installed (or called
    # from a racing thread) after stop_profiler — without this guard eager
    # op events accumulate in _events/_timeline forever on long runs
    if not _active[0]:
        return
    rec = _events["op::" + name]
    rec[0] += 1
    rec[1] += seconds
    t1 = time.perf_counter()
    _timeline_add("op::" + name, t1 - seconds, t1)


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    """Begin host-event + per-op aggregation; with ``log_dir`` also start
    a jax.profiler XPlane trace there (view in TensorBoard/Perfetto —
    reference analogue: device_tracer.cc:464 timeline capture).

    Workflow::

        profiler.start_profiler(log_dir="/tmp/trace")
        ... train steps ...
        profiler.stop_profiler()
        print(profiler.summary())           # host events + eager op table
        # device timeline: tensorboard --logdir /tmp/trace
    """
    _active[0] = True
    _events.clear()
    _timeline.clear()
    from ..core.tensor import set_op_profile_hook
    set_op_profile_hook(_op_hook)
    if log_dir:
        try:
            jax.profiler.start_trace(log_dir)
            _tls.trace_dir = log_dir
        except Exception as e:  # host aggregation must survive a backend
            import warnings      # that cannot produce an xplane trace
            warnings.warn(f"xplane trace not started ({e!r}); host-side "
                          "event aggregation continues", RuntimeWarning)


def stop_profiler(sorted_key=None, profile_path=None):
    """End aggregation. With ``profile_path`` the summary table (sorted by
    ``sorted_key``: 'calls'/'total'/'avg', default total) is written there —
    fluid.profiler.stop_profiler parity, which dumped its per-op table to
    that path."""
    _active[0] = False
    from ..core.tensor import set_op_profile_hook
    set_op_profile_hook(None)
    if getattr(_tls, "trace_dir", None):
        try:
            jax.profiler.stop_trace()
        finally:
            _tls.trace_dir = None
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(summary(sorted_key or "total") + "\n")


# fluid's 'min'/'max' sort keys are NOT accepted: per-event min/max are
# not tracked here, and silently sorting by total instead would misreport
# — unknown keys raise so the caller learns the supported set
_SUMMARY_KEYS = {
    "calls": lambda cnt, tot: cnt,
    "total": lambda cnt, tot: tot,
    "avg": lambda cnt, tot: tot / max(cnt, 1),
    "ave": lambda cnt, tot: tot / max(cnt, 1),   # fluid alias for avg
}


def summary(sorted_by="total"):
    """Host-event + eager-op table, sorted DESC by ``sorted_by``
    ('calls' | 'total' | 'avg')."""
    keyfn = _SUMMARY_KEYS.get(sorted_by or "total")
    if keyfn is None:
        raise ValueError(f"summary: sorted_by must be one of "
                         f"{sorted(_SUMMARY_KEYS)}, got {sorted_by!r}")
    rows = sorted(_events.items(), key=lambda kv: -keyfn(kv[1][0], kv[1][1]))
    lines = [f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} {'Avg(ms)':>12}"]
    for name, (count, total) in rows:
        lines.append(f"{name:<40} {count:>8} {total * 1e3:>12.3f} "
                     f"{total * 1e3 / max(count, 1):>12.3f}")
    return "\n".join(lines)


def chrome_trace_doc() -> dict:
    """The host-timeline chrome-trace document as a dict (what
    ``export_chrome_tracing`` writes) — served in-memory by the admin
    server's ``/debug/profile`` endpoint."""
    events = [{"name": name, "ph": "X", "ts": ts, "dur": dur,
               "pid": 0, "tid": tid % 100000, "cat": "host"}
              for name, ts, dur, tid in _timeline]
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _write_chrome_trace(path: str) -> str:
    import json

    with open(path, "w") as f:
        json.dump(chrome_trace_doc(), f)
    return path


def export_chrome_tracing(path: str, worker_name: Optional[str] = None):
    """Chrome-trace exporter, two forms (chrome://tracing / Perfetto JSON;
    the reference emits its profiler.proto timeline the same way,
    device_tracer.cc GenProfile:496).

    - Direct: a ``*.json`` path writes the current host timeline NOW and
      returns the path.
    - Handler factory (paddle.profiler.export_chrome_tracing parity): any
      other path is treated as a directory and a callable is returned for
      ``Profiler(on_trace_ready=...)``; each closed record window writes
      ``<dir>/<worker>_chrome_trace_<n>.json``.

    Device-side kernels live in the XPlane trace captured via
    ``start_profiler(log_dir=...)`` / ``export_tensorboard``; this file
    covers the host lanes (RecordEvent blocks + eager op dispatches)."""
    import os

    if path.endswith(".json"):
        return _write_chrome_trace(path)

    dir_name, worker = path, worker_name or "host"
    counter = [0]

    def handler(prof) -> str:
        os.makedirs(dir_name, exist_ok=True)
        counter[0] += 1
        return _write_chrome_trace(os.path.join(
            dir_name, f"{worker}_chrome_trace_{counter[0]}.json"))

    handler.dir_name = dir_name
    return handler


def export_tensorboard(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready handler for TensorBoard: the device-side XPlane
    trace is captured into ``dir_name`` (Profiler adopts it as its
    ``log_dir`` — jax.profiler writes plugins/profile/<ts> subdirs there,
    viewable with ``tensorboard --logdir dir_name``), and each closed
    window also writes the host summary table next to it."""
    import os

    counter = [0]

    def handler(prof) -> str:
        os.makedirs(dir_name, exist_ok=True)
        counter[0] += 1
        path = os.path.join(
            dir_name, f"{worker_name or 'host'}_summary_{counter[0]}.txt")
        with open(path, "w") as f:
            f.write(summary() + "\n")
        return path

    handler.log_dir = dir_name        # Profiler picks this up for xplane
    return handler


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default", log_dir=None,
             sorted_key="total"):
    """fluid.profiler.profiler context analogue."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler()
        print(summary(sorted_key))


def profile_train_step(step, batch, iters: int = 10, warmup: int = 2):
    """Attribute a TrainStep's wall time: compile vs host prep vs dispatch
    vs device execute (reference analogue: the per-op timeline totals of
    platform/profiler.cc, collapsed to the phases that exist under XLA's
    one-executable-per-step model).

    Returns a dict:
      compile_s       time of the first (cold) call incl. compilation;
                      ~0 when the persistent compile cache is warm
      host_ms         python-side prep per step (batch placement, flatten,
                      signature lookup) — measured by timing dispatch-only
                      calls minus the jitted dispatch itself
      dispatch_ms     time for step() to RETURN (async dispatch)
      step_ms         full step latency incl. device work (readback-timed)
      device_ms_est   step_ms minus host prep: device execute + dispatch
                      enqueue time (>= 0)
    """
    import numpy as np

    def readback(loss):
        return float(np.asarray(loss._data if hasattr(loss, "_data")
                                else loss))

    t0 = time.perf_counter()
    readback(step(*batch))
    compile_s = time.perf_counter() - t0

    for _ in range(warmup):
        step(*batch)
    readback(step(*batch))

    # host-side prep: everything __call__ does before the XLA dispatch
    t0 = time.perf_counter()
    for _ in range(iters):
        raw = [b._data if hasattr(b, "_data") else b for b in batch]
        raw = step._place_batch(raw)
        jax.tree_util.tree_flatten(raw)
    host_ms = (time.perf_counter() - t0) / iters * 1e3

    # dispatch: call returns as soon as XLA enqueues
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(*batch)
    dispatch_ms = (time.perf_counter() - t0) / iters * 1e3
    readback(loss)

    # full latency: readback forces device completion each step
    t0 = time.perf_counter()
    for _ in range(iters):
        readback(step(*batch))
    step_ms = (time.perf_counter() - t0) / iters * 1e3

    return {
        "compile_s": compile_s,
        "host_ms": host_ms,
        "dispatch_ms": dispatch_ms,
        "step_ms": step_ms,
        "device_ms_est": max(0.0, step_ms - host_ms),
    }


class ProfilerState:
    """paddle.profiler.ProfilerState parity: the per-step scheduler
    states. RECORD_AND_RETURN marks the LAST record step of a window —
    the step after it closes the window and fires on_trace_ready."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget:
    """paddle.profiler.ProfilerTarget parity tokens. On this stack the
    host lanes (CPU) and the XLA device trace (captured together in the
    XPlane file) are not separately selectable — targets are accepted and
    recorded for API parity."""
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0):
    """paddle.profiler.make_scheduler parity: a step->ProfilerState
    function cycling CLOSED(closed) -> READY(ready) -> RECORD(record),
    with the window's last record step flagged RECORD_AND_RETURN.
    ``repeat=0`` cycles forever; ``skip_first`` steps are CLOSED before
    the first cycle."""
    if record <= 0:
        raise ValueError("make_scheduler: record must be >= 1")
    if closed < 0 or ready < 0 or repeat < 0 or skip_first < 0:
        raise ValueError("make_scheduler: closed/ready/repeat/skip_first "
                         "must be >= 0")
    cycle = closed + ready + record

    def scheduler(step: int) -> int:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        return (ProfilerState.RECORD_AND_RETURN if pos == cycle - 1
                else ProfilerState.RECORD)

    return scheduler


class Profiler:
    """paddle.profiler.Profiler parity over jax.profiler + the host
    aggregation above.

    ``scheduler`` is a step->ProfilerState callable (see
    :func:`make_scheduler`) or a ``(start, end)`` tuple recording steps in
    ``[start, end)``; None records everything between start() and stop().
    Each closed record window fires ``on_trace_ready(self)`` (see
    :func:`export_chrome_tracing` / :func:`export_tensorboard` for
    handler factories). ``step()`` advances the schedule — call it once
    per training step.
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir="./profiler_log", timer_only=False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            if not (0 <= start < end):
                raise ValueError(f"scheduler tuple must be 0 <= start < "
                                 f"end, got {scheduler!r}")
            scheduler = make_scheduler(closed=start, ready=0,
                                       record=end - start, repeat=1)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        # a TensorBoard handler carries the xplane dir it wants traces in
        self.log_dir = getattr(on_trace_ready, "log_dir", None) or log_dir
        self.timer_only = timer_only
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._recording = False
        self.windows = 0          # closed record windows so far

    # -- window plumbing ---------------------------------------------------
    def _begin_window(self):
        if self._recording:
            return
        start_profiler(log_dir=None if self.timer_only else self.log_dir)
        self._recording = True

    def _end_window(self):
        if not self._recording:
            return
        stop_profiler()
        self._recording = False
        self.windows += 1
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def _apply(self, state: int):
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_window()
        elif self._recording:
            self._end_window()
        self.state = state

    # -- public API --------------------------------------------------------
    def start(self):
        self.step_num = 0
        self._apply(self.scheduler(0) if self.scheduler
                    else ProfilerState.RECORD)
        return self

    def step(self, num_samples=None):
        """Advance one training step; closes a window right after its
        RECORD_AND_RETURN step, per the reference scheduler contract."""
        if self.state == ProfilerState.RECORD_AND_RETURN:
            self._end_window()
        self.step_num += 1
        if self.scheduler is not None:
            self._apply(self.scheduler(self.step_num))

    def stop(self):
        # a window open at stop() — unscheduled run, early loop break,
        # exception mid-RECORD — is exported like any other: partial data
        # beats silently discarding everything recorded so far (the
        # reference Profiler.stop() also exports from RECORD states)
        self._end_window()
        self.state = ProfilerState.CLOSED

    def summary(self, sorted_by="total"):
        return summary(sorted_by)

    def export(self, path: str, format: str = "json") -> str:
        """Write the newest host timeline as a chrome trace (format
        'json'; paddle's Profiler.export parity)."""
        if format != "json":
            raise ValueError(f"export: only 'json' (chrome trace) is "
                             f"supported, got {format!r}")
        return _write_chrome_trace(path)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
