"""Pallas batched gather-matmul (bgmv) for multi-tenant LoRA serving.

The Punica/S-LoRA primitive: a batch where every row may use a DIFFERENT
low-rank adapter. Adapter weights live in stacked pools
``A [n_adapters, r, E]`` / ``B [n_adapters, r, O]``; a per-slot int32
``ids`` row picks which adapter serves each batch element, and the fused
shrink + expand

    delta[b] = (x[b] @ A[ids[b]].T) @ B[ids[b]]        # [S,E]->[S,r]->[S,O]

is added to the base model's fused-QKV projection inside the serving
dispatches (models/gpt.py). Row 0 of the pools is the reserved ZERO
adapter — base-model requests ride the same compiled program and their
delta is exactly 0.0, so mixing adapted and plain requests in one batch
costs no extra dispatch.

Kernel shape: grid ``(B,)`` with the adapter ids scalar-prefetched; the
BlockSpec index maps route block ``ids[i]`` of each pool straight into
VMEM, so the gathered ``[B, r, E]``/``[B, r, O]`` adapter copies the XLA
fallback materializes never exist in HBM — the gather IS the access
path, exactly like the paged flash-decode kernel's block-table indexing.
Both matmuls accumulate in f32 (``preferred_element_type``).

Dispatch follows the ONE convention of this layer (see
ops/pallas/__init__): kill switch ``FLAGS_pallas_bgmv`` whose off
position is the bit-compatible XLA gather+einsum oracle
(:func:`bgmv_xla`), TPU-only unless ``FLAGS_pallas_interpret``, counted
fallbacks, a registry row, a parity test and a bench line.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams shim)

__all__ = ["bgmv", "bgmv_xla"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def bgmv_xla(x, a, b, ids):
    """XLA oracle: gather each row's adapter then shrink + expand.

    ``x``: ``[B, S, E]``; ``a``: ``[A, r, E]``; ``b``: ``[A, r, O]``;
    ``ids``: ``[B]`` int32 adapter rows. Returns ``[B, S, O]`` in x's
    dtype — the flags-off fallback the kernel must match bit-for-bit on
    identical inputs (both paths accumulate in f32).
    """
    aw = a[ids]                                          # [B, r, E]
    bw = b[ids]                                          # [B, r, O]
    h = jnp.einsum("bse,bre->bsr", x.astype(jnp.float32),
                   aw.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    out = jnp.einsum("bsr,bro->bso", h, bw.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


def _bgmv_kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
    x = x_ref[0].astype(jnp.float32)                     # [S, E]
    a = a_ref[0].astype(jnp.float32)                     # [r, E]
    b = b_ref[0].astype(jnp.float32)                     # [r, O]
    # shrink: h[s, r] = x[s] . a[r]  (contract over E)
    h = jax.lax.dot_general(
        x, a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [S, r]
    # expand: o[s, o] = h[s] . b[:, o]  (contract over r)
    o = jax.lax.dot_general(
        h, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [S, O]
    o_ref[0] = o.astype(o_ref.dtype)


def bgmv(x, a, b, ids):
    """Batched gather-matmul: per-row adapter shrink + expand.

    Same contract as :func:`bgmv_xla`; the adapter pools are read in
    place via scalar-prefetch indexing (one ``[r, E]`` + ``[r, O]``
    DMA per batch row, no HBM gather).
    """
    B, S, E = x.shape
    r = a.shape[1]
    O = b.shape[2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                           # ids
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, S, E), lambda i, ids: (i, 0, 0)),
            pl.BlockSpec((1, r, E), lambda i, ids: (ids[i], 0, 0)),
            pl.BlockSpec((1, r, O), lambda i, ids: (ids[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, S, O), lambda i, ids: (i, 0, 0)),
    )
    return pl.pallas_call(
        functools.partial(_bgmv_kernel),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, O), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(ids.astype(jnp.int32), x, a, b)
