"""Shared stateless-RNG pieces for the Pallas kernels.

One fmix32 + threshold definition keeps the flash-attention in-kernel
dropout and the fused dropout kernel bit-identical by construction (the
backward passes REGENERATE masks from these)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fmix32", "keep_threshold"]


def fmix32(x):
    """murmur3 finalizer over uint32 lanes."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def keep_threshold(rate: float):
    """uint32 threshold with P(hash >= t) = 1 - rate."""
    return jnp.uint32(min(rate, 0.999999) * 4294967296.0)
