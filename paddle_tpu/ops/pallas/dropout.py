"""Fused dropout kernel (TPU).

reference parity: the reference's dropout op generates a mask with
curand, stores it, and multiplies (operators/dropout_op.cu); under XLA
the same composition materializes the random bits, the keep mask, and
the product as separate HBM round-trips (~4x the minimal traffic on a
BERT-base step).

TPU-native: ONE pass — the kernel reads x, computes the keep decision
from a stateless murmur3-finalizer hash over the absolute element index
(same construction as the flash kernel's in-kernel dropout), and writes
x * keep / (1-p). Nothing else touches HBM. The backward REGENERATES the
identical mask from the seed (custom_vjp), so no mask is ever stored.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_dropout"]

_LANES = 128
_ROWS = 512            # rows per program: 512x128 f32 tile = 256KB


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _keep_mask(idx, seed0, seed1, rate):
    """Keep decision over absolute element indices (shared fmix32)."""
    from .rng import fmix32, keep_threshold
    x = fmix32(idx.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
               ^ seed0.astype(jnp.uint32)
               ^ (seed1.astype(jnp.uint32) << 1))
    return x >= keep_threshold(rate)


def _drop_kernel(seed_ref, x_ref, o_ref, *, rate):
    i = pl.program_id(0)
    rows, lanes = x_ref.shape
    base = i * rows * lanes
    idx = base + (jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
                  * lanes
                  + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1))
    keep = _keep_mask(idx, seed_ref[0], seed_ref[1], rate)
    inv = 1.0 / (1.0 - rate)
    x = x_ref[...]
    o_ref[...] = jnp.where(keep, x * jnp.asarray(inv, x.dtype),
                           jnp.zeros_like(x))


def _run(x2d, seed, rate):
    R, C = x2d.shape
    # bound the BLOCK jointly over rows x lane-width: keep in+out blocks
    # around 256KB f32 each regardless of C (wide activations otherwise
    # blow the ~16M VMEM with 512-row blocks). rb is a power of two >= 8
    # (sublane multiple) that divides R (caller guarantees R % 8 == 0).
    budget = max(8, _ROWS * _LANES // C)
    rb = 8
    while rb * 2 <= budget and R % (rb * 2) == 0:
        rb *= 2
    nb = R // rb
    return pl.pallas_call(
        functools.partial(_drop_kernel, rate=rate),
        grid=(nb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((rb, C), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((rb, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=_interpret(),
    )(seed, x2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _dropout(x2d, seed_f, rate):
    return _run(x2d, jax.lax.bitcast_convert_type(seed_f, jnp.int32), rate)


def _dropout_fwd(x2d, seed_f, rate):
    return _dropout(x2d, seed_f, rate), seed_f


def _dropout_bwd(rate, seed_f, g):
    # identical mask regenerated from the seed: d(drop(x))/dx = mask/(1-p)
    dg = _run(g, jax.lax.bitcast_convert_type(seed_f, jnp.int32), rate)
    return dg, jnp.zeros_like(seed_f)


_dropout.defvjp(_dropout_fwd, _dropout_bwd)


def fused_dropout(x, rate: float, key):
    """Single-pass dropout over an array of any shape (upscale_in_train).

    Pads the flattened input to a whole number of (512, 128) tiles; the
    pad cost is bounded by one tile (64K elements)."""
    rate = float(rate)
    if rate <= 0.0:
        return x
    if rate >= 1.0:
        return jnp.zeros_like(x)
    words = jax.random.key_data(key).ravel()[:2].astype(jnp.uint32)
    seed_f = jax.lax.bitcast_convert_type(words, jnp.float32)
    n = x.size
    # natural 2D view when the trailing dim is lane-aligned: the reshape
    # [..., C] -> [n//C, C] is a free bitcast (no relayout copies)
    C = x.shape[-1] if (x.ndim >= 2 and x.shape[-1] % _LANES == 0
                        and x.shape[-1] <= 4096) else _LANES
    if n % C == 0 and (n // C) % 8 == 0:
        out = _dropout(x.reshape(n // C, C), seed_f, rate)
        return out.reshape(x.shape)
    tile = _ROWS * _LANES
    padded = (n + tile - 1) // tile * tile
    flat = x.reshape(-1)
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    out = _dropout(flat.reshape(padded // _LANES, _LANES), seed_f, rate)
    return out.reshape(-1)[:n].reshape(x.shape)
