"""Pallas fused chunked (streamed-vocab) cross-entropy (TPU).

The kernel form of :mod:`paddle_tpu.nn.chunked_ce`'s hard-label path —
the TPU-native replacement for the reference's fused CUDA
``softmax_with_cross_entropy`` op (reference:
paddle/fluid/operators/softmax_with_cross_entropy_op.cu).

Why a kernel when the XLA streaming loop already avoids the full-vocab
f32 materialization: the ``fori_loop`` body is a sequence of separate
HLO ops (dynamic-slice → convert → reduce → …) that XLA schedules as
individual HBM round trips per chunk, and the backward's
read-modify-write ``dynamic_update_slice`` forces a full extra
read+write of the gradient buffer. Here each ``[block_n, chunk]`` tile
is VMEM-resident for its whole fwd (online (m, s) logsumexp recurrence)
or bwd (``(softmax - onehot) * g``) pass: the logits are read exactly
once forward and once backward, the dlogits tile is written exactly
once, and the row statistics ride a narrow 8-lane tile like
flash_attention's lse.

Semantics are pinned to ``nn.chunked_ce._ce_hard``: f32 accumulation,
loss = lse - logits[n, label[n]] in f32, dlogits = (p - onehot) * g in
the logits dtype. ignore_index / class weights / reductions stay in the
differentiable epilogue OUTSIDE the kernel (nn/functional.py), so the
public ``F.cross_entropy`` semantics are untouched. Soft labels keep
the XLA streaming path.

Grid/blocking: ``(ceil(N / block_n), ceil(V / chunk))`` with the vocab
sweep innermost (``arbitrary``); ``block_n`` rows per program
(``PTPU_CE_BLOCK_N``, default 128), chunk width from
``FLAGS_chunked_ce_chunk`` (multiples of 128 keep Mosaic lane tiles
exact; any tail is masked in-kernel, never padded in HBM).

Tests run these kernels on CPU via the Pallas interpreter
(FLAGS_pallas_interpret; the ``pallas`` pytest marker).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams shim)

__all__ = ["chunked_ce_loss", "DEFAULT_BLOCK_N"]

DEFAULT_BLOCK_N = 128
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_n() -> int:
    """Row-block override following the PTPU_FLASH_BLOCK_Q/K convention."""
    raw = os.environ.get("PTPU_CE_BLOCK_N")
    if not raw:
        return DEFAULT_BLOCK_N
    try:
        b = int(raw)
    except ValueError:
        raise ValueError(
            f"PTPU_CE_BLOCK_N={raw!r}: the chunked-CE row-block override "
            f"must be a positive integer number of rows") from None
    if b <= 0 or b % 8:
        raise ValueError(
            f"PTPU_CE_BLOCK_N={b}: the chunked-CE row-block override "
            f"must be a positive multiple of 8 (the TPU sublane tile) — "
            f"Mosaic would reject the block shape with an error that "
            f"never names this variable")
    return b


def _col_ids(j, block_n: int, chunk: int):
    """Absolute vocab column ids of chunk ``j``, [block_n, chunk]."""
    return j * chunk + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, chunk), 1)


# ---------------------------------------------------------------------------
# forward: online logsumexp over the vocab sweep
# ---------------------------------------------------------------------------


def _lse_kernel(logits_ref, lse_ref, m_scr, s_scr, *, block_n, chunk, V):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        s_scr[:] = jnp.zeros_like(s_scr)

    sl = logits_ref[...].astype(jnp.float32)             # [bn, chunk]
    # tail chunk of a non-multiple vocab: mask the overhang columns
    sl = jnp.where(_col_ids(j, block_n, chunk) < V, sl, NEG_INF)
    m_prev = m_scr[:, :1]                                # [bn, 1]
    m_new = jnp.maximum(m_prev, jnp.max(sl, axis=1, keepdims=True))
    # fully-masked tile: m_new stays NEG_INF; shift by 0 to avoid inf-inf
    shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
    p = jnp.exp(sl - shift)                              # masked cols -> 0
    s_scr[:] = jnp.broadcast_to(
        s_scr[:, :1] * jnp.exp(m_prev - shift)
        + jnp.sum(p, axis=1, keepdims=True), s_scr.shape)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        m = m_scr[:, :1]
        s = s_scr[:, :1]
        safe_s = jnp.where(s == 0.0, 1.0, s)
        lse = jnp.where(s == 0.0, NEG_INF, m + jnp.log(safe_s))
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _online_lse(logits, block_n: int, chunk: int):
    """Row logsumexp of [N, V] logits; returns the narrow [N, 8] f32
    row-stat tile (column 0 is the value — same convention as
    flash_attention's lse output)."""
    N, V = logits.shape
    ni, nj = pl.cdiv(N, block_n), pl.cdiv(V, chunk)
    return pl.pallas_call(
        functools.partial(_lse_kernel, block_n=block_n, chunk=chunk, V=V),
        grid=(ni, nj),
        in_specs=[pl.BlockSpec((block_n, chunk), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((block_n, 8), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, 8), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((block_n, 8), jnp.float32),
            pltpu.VMEM((block_n, 8), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(logits)


# ---------------------------------------------------------------------------
# backward: dlogits = (softmax - onehot) * g, one pass, no accumulation
# ---------------------------------------------------------------------------


def _dlogits_kernel(logits_ref, lab_ref, lse_ref, g_ref, dl_ref, *,
                    block_n, chunk, V):
    j = pl.program_id(1)
    sl = logits_ref[...].astype(jnp.float32)             # [bn, chunk]
    lse = lse_ref[:, :1]                                 # [bn, 1]
    cols = _col_ids(j, block_n, chunk)
    # fully-padded row (grid overhang): lse = NEG_INF -> shift by 0 so
    # exp stays finite; the row's write is dropped by the grid bounds
    p = jnp.exp(sl - jnp.where(lse == NEG_INF, 0.0, lse))
    onehot = (cols == lab_ref[:, :1]).astype(jnp.float32)
    d = (p - onehot) * g_ref[:, :1]
    d = jnp.where(cols < V, d, 0.0)
    dl_ref[...] = d.astype(dl_ref.dtype)


def _dlogits(logits, labels, lse, g, block_n: int, chunk: int):
    N, V = logits.shape
    ni, nj = pl.cdiv(N, block_n), pl.cdiv(V, chunk)
    row8 = pl.BlockSpec((block_n, 8), lambda i, j: (i, 0))
    return pl.pallas_call(
        functools.partial(_dlogits_kernel, block_n=block_n, chunk=chunk,
                          V=V),
        grid=(ni, nj),
        in_specs=[
            pl.BlockSpec((block_n, chunk), lambda i, j: (i, j)),
            row8,                                        # labels [N, 8]
            row8,                                        # lse    [N, 8]
            row8,                                        # g      [N, 8]
        ],
        out_specs=pl.BlockSpec((block_n, chunk), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, V), logits.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=_interpret(),
    )(logits, labels, lse, g)


def _row8(x, dtype):
    """Broadcast a [N] per-row vector to the narrow 8-lane tile the
    kernels consume (Mosaic's minimum lane width; 16x less HBM than a
    128-lane broadcast)."""
    return jnp.broadcast_to(x.astype(dtype)[:, None], (x.shape[0], 8))


# ---------------------------------------------------------------------------
# custom VJP (same signature/semantics as nn.chunked_ce._ce_hard)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ce(block_n: int, chunk: int, logits, labels):
    loss, _ = _ce_fwd(block_n, chunk, logits, labels)
    return loss


def _ce_fwd(block_n: int, chunk: int, logits, labels):
    lse8 = _online_lse(logits, block_n, chunk)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    loss = lse8[:, 0] - tgt.astype(jnp.float32)
    return loss, (logits, labels, lse8)


def _ce_bwd(block_n: int, chunk: int, res, g):
    logits, labels, lse8 = res
    grad = _dlogits(logits, _row8(labels, jnp.int32), lse8,
                    _row8(g, jnp.float32), block_n, chunk)
    return grad, np.zeros(labels.shape, dtype=jax.dtypes.float0)


_ce.defvjp(_ce_fwd, _ce_bwd)


def chunked_ce_loss(logits, labels, chunk: int):
    """Fused streamed hard-label NLL: ``logits [N, V]``, ``labels [N]``
    int32 class ids (the caller maps ignore_index to a safe id and masks
    the result — same contract as ``nn.chunked_ce.hard_nll``). Returns
    f32 ``[N]`` per-row losses; differentiable in ``logits``."""
    N, V = logits.shape
    chunk = max(1, min(int(chunk), V))
    # cap at N rounded UP to the sublane tile: a short batch gets one
    # 8-aligned block (grid-overhang rows are masked/dropped in-kernel)
    block_n = min(_block_n(), max(8, -(-N // 8) * 8))
    return _ce(int(block_n), int(chunk), logits,
               labels.astype(jnp.int32))
