"""Pallas paged flash-decode attention (TPU serving hot path).

Decode-step attention over the block-structured KV pool of
:mod:`paddle_tpu.serving.kv_cache` — the kernel form of PagedAttention
(vLLM, SOSP '23) and the TPU-native replacement for the reference's
fused decode attention (reference: fused_multi_transformer_op.cu's
masked attention over the growing cache).

The XLA fallback (``models/gpt.py _paged_attention``) materializes the
slot-contiguous context first: ``gather_pages`` writes a dense
``[B, MB*bs, H, D]`` copy of every slot's pages to HBM, the masked SDPA
reads it back, and most of that traffic is wasted — a slot at position
``p`` only owns ``ceil(p/bs)`` of its ``MB`` table entries, the rest
point at the scratch page. Here the block table IS the access path:
a scalar-prefetch grid ``(slots, MB)`` maps logical block ``j`` of slot
``b`` straight to physical page ``table[b, j]`` in the BlockSpec index
map, so each page is DMA'd from the pool into VMEM exactly once and the
gathered context never exists in HBM. Blocks past the slot's position
are compute-skipped (their table entries alias the scratch page, so
their DMA is a reread of one hot page, not pool traffic).

Online softmax over the block sweep (running (m, l) row stats per head,
f32 accumulation), additive key masking by per-slot position — the same
math as the fallback's ``cols <= pos`` mask, so decode stays TOKEN-EXACT
against the dense path (pinned in tests/test_pallas_kernels.py).

All heads of a page ride one program (the per-head q row is [1, D];
batching heads keeps the MXU/VPU fed); the page size ``bs`` set by
``ServingConfig.block_size`` is the KV block size — there is no separate
kernel block knob.

Tests run this kernel on CPU via the Pallas interpreter
(FLAGS_pallas_interpret; the ``pallas`` pytest marker).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams shim)

__all__ = ["paged_decode_attention", "paged_decode_attention_quant"]

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(tbl_ref, pos_ref, q_ref, *refs, scale, bs, H, D,
                   quant=False):
    if quant:
        # int8 pools ride with their per-(row, head) f32 scale blocks
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    b, j = pl.program_id(0), pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    p = pos_ref[b]

    # blocks wholly past the written positions contribute nothing: skip
    # the compute (their table entries alias the scratch page, so the
    # page DMA above cost one hot-page reread, not pool bandwidth)
    @pl.when(j * bs <= p)
    def _step():
        q = q_ref[0].astype(jnp.float32)                 # [H, D]
        k = k_ref[0].astype(jnp.float32)                 # [bs, H, D]
        v = v_ref[0].astype(jnp.float32)
        if quant:
            # identical math to kv_cache.dequant_pages, so the kernel
            # stays token-exact against the XLA gather fallback
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # s[h, c] = q[h] . k[c, h] — heads are the batch dimension
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale  # [H, bs]
        cols = j * bs + jax.lax.broadcasted_iota(jnp.int32, (H, bs), 1)
        # slot b sees written positions 0..p (current token included) —
        # identical to the fallback's additive key mask
        s = jnp.where(cols <= p, s, NEG_INF)
        m_prev = m_scr[:, :1]                            # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        pr = jnp.exp(s - shift)                          # masked -> 0
        alpha = jnp.exp(m_prev - shift)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, :1] + jnp.sum(pr, axis=1, keepdims=True),
            l_scr.shape)
        # acc[h] += pr[h] @ v[:, h]
        pv = jax.lax.dot_general(
            pr, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)          # [H, D]
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)             # inactive slot
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_table, pos, *,
                           scale: float):
    """One decode step of attention over paged KV state.

    ``q``: ``[B, H, D]`` (the decode token's query, S dim squeezed);
    ``k_pages``/``v_pages``: ``[P, bs, H, D]`` pools;
    ``block_table``: ``[B, MB]`` int32 physical-page ids;
    ``pos``: ``[B]`` int32 per-slot positions (the current token's
    logical index — attended inclusively, like the XLA fallback).
    Returns ``[B, H, D]`` in q's dtype.
    """
    B, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_table.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                           # table, pos
        grid=(B, MB),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, tbl, p: (b, 0, 0)),
            pl.BlockSpec((1, bs, H, D),
                         lambda b, j, tbl, p: (tbl[b, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, H, D),
                         lambda b, j, tbl, p: (tbl[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, j, tbl, p: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 8), jnp.float32),
            pltpu.VMEM((H, 8), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale), bs=bs,
                          H=H, D=D),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(block_table.astype(jnp.int32), pos.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_decode_attention_quant(q, k_pages, k_scales, v_pages, v_scales,
                                 block_table, pos, *, scale: float):
    """Decode attention over an int8-quantized paged pool
    (``FLAGS_serve_kv_quant=int8``).

    Same contract as :func:`paged_decode_attention`, plus the parallel
    f32 scale pools ``k_scales``/``v_scales`` ``[P, bs, H]``. The scale
    blocks ride the SAME block-table index maps as their pages, so the
    dequantize (``int8 * scale``) happens in VMEM right before the
    existing online-softmax sweep — the dequantized context never exists
    in HBM. Must match ``kv_cache.gather_pages_quant`` + masked SDPA
    token-exactly (same dequant math, f32 accumulation).
    """
    B, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_table.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                           # table, pos
        grid=(B, MB),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, tbl, p: (b, 0, 0)),
            pl.BlockSpec((1, bs, H, D),
                         lambda b, j, tbl, p: (tbl[b, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, H),
                         lambda b, j, tbl, p: (tbl[b, j], 0, 0)),
            pl.BlockSpec((1, bs, H, D),
                         lambda b, j, tbl, p: (tbl[b, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, H),
                         lambda b, j, tbl, p: (tbl[b, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, j, tbl, p: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 8), jnp.float32),
            pltpu.VMEM((H, 8), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale), bs=bs,
                          H=H, D=D, quant=True),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(block_table.astype(jnp.int32), pos.astype(jnp.int32),
      q, k_pages, k_scales, v_pages, v_scales)
