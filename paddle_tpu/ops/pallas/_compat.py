"""jax-version compatibility shims for the kernel modules.

Imported for its side effect (``from . import _compat``) by every
kernel module BEFORE it touches ``pltpu.CompilerParams`` — one place to
track a jax rename instead of a per-kernel copy of the patch.
"""

from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 names the dataclass TPUCompilerParams; same fields
    pltpu.CompilerParams = pltpu.TPUCompilerParams
