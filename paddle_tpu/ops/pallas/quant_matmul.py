"""Pallas int8 quantized matmul (TPU).

The TPU-native replacement for the reference's slim int8 inference
kernels (reference: the MKLDNN/TensorRT int8 gemms behind
post_training_quantization.py) — a per-output-channel symmetric
int8 x int8 -> int32 matmul with a dequantize epilogue, running on the
MXU's native int8 path instead of dequantizing weights back to float
before the gemm (the pre-kernel ``slim.QuantizedLinear`` behavior this
replaces: weight HBM traffic stays at 1/4 the f32 bytes AND the MXU
runs at int8 rate).

Scheme (one convention across serving + flag-gated AMP training):

- weights: per-output-channel symmetric int8, ``w_q [K, N]`` with
  ``w_scale [N]`` f32 (``quantize_per_channel``, the observer
  ``slim._channel_scales`` / ``nn.quant`` records);
- activations: per-tensor symmetric int8 — a static calibrated scale
  (``act_scale``) or a dynamic absmax resolved in XLA right before the
  kernel (one cheap fused reduction; the quantize itself is an
  elementwise pass XLA fuses into the surrounding graph);
- kernel: grid ``(M/bm, N/bn, K/bk)``, k innermost, int32 VMEM
  accumulator, epilogue ``acc * (act_scale * w_scale[n])`` at the last
  k step in f32, cast to the activation dtype.

``int8_amp_linear`` wraps the kernel in a custom VJP whose backward is
the straight-through dense pair (``dx = g @ w^T``, ``dw = x^T @ g`` on
the UNquantized operands) so the flag-gated AMP path trains through
quantization noise without int8 gradients.

Block sizes: ``PTPU_INT8_BLOCK_M/N/K`` (defaults 128/128/512); N and K
must be multiples of 128 (lane tiles) — other geometries fall back.
Tests run the kernel on CPU via the Pallas interpreter
(FLAGS_pallas_interpret; the ``pallas`` pytest marker).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams shim)

__all__ = ["int8_matmul", "int8_linear", "int8_amp_linear",
           "quantize_per_channel", "quantize_per_tensor",
           "matmul_shapes_supported"]

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_K = 512


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _env_block(var: str, default: int) -> int:
    raw = os.environ.get(var)
    if not raw:
        return default
    try:
        b = int(raw)
    except ValueError:
        raise ValueError(
            f"{var}={raw!r}: the int8-matmul block override must be an "
            f"integer") from None
    if b <= 0 or b % 128:
        raise ValueError(
            f"{var}={b}: the int8-matmul block override must be a "
            f"positive multiple of 128 (the TPU lane tile)")
    return b


def _divisor_block(dim: int, requested: int) -> int:
    """Largest multiple of 128 dividing ``dim``, capped at ``requested``."""
    start = (min(requested, dim) // 128) * 128
    for b in range(start, 127, -128):
        if dim % b == 0:
            return b
    return 128


def matmul_shapes_supported(K: int, N: int) -> bool:
    """The kernel's geometry gate: lane-tiled contraction and output
    channels. M is free (the row grid is ceil-divided and padded)."""
    return K % 128 == 0 and N % 128 == 0


# ---------------------------------------------------------------------------
# quantizers (XLA; fused into the surrounding graph)
# ---------------------------------------------------------------------------


def quantize_per_channel(w, axis: int = 1, bits: int = 8):
    """Symmetric per-channel quantization of a [K, N] weight along the
    output axis: returns (w_q int8, scale f32 [N])."""
    qmax = 2.0 ** (bits - 1) - 1
    red = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=red)
    scale = jnp.maximum(absmax / qmax, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32)
                           / jnp.expand_dims(scale, red)),
                 -qmax, qmax).astype(jnp.int8)
    return q, scale


def quantize_per_tensor(x, act_scale=None, bits: int = 8):
    """Symmetric per-tensor quantization of activations: returns
    (x_q int8, scale f32 scalar). ``act_scale=None`` = dynamic absmax."""
    qmax = 2.0 ** (bits - 1) - 1
    x32 = x.astype(jnp.float32)
    if act_scale is None:
        act_scale = jnp.maximum(jnp.max(jnp.abs(x32)) / qmax, 1e-8)
    else:
        act_scale = jnp.asarray(act_scale, jnp.float32)
    q = jnp.clip(jnp.round(x32 / act_scale), -qmax, qmax).astype(jnp.int8)
    return q, act_scale


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _mm_kernel(xq_ref, wq_ref, ws_ref, as_ref, o_ref, acc_scr, *, out_dtype):
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # int8 x int8 -> int32 on the MXU's native int8 path
    acc_scr[:] += jax.lax.dot_general(
        xq_ref[...], wq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == nk - 1)
    def _finish():
        # dequantize epilogue: one f32 multiply per output element
        scale = as_ref[0, 0] * ws_ref[0, :]              # [bn]
        o_ref[...] = (acc_scr[:].astype(jnp.float32)
                      * scale[None, :]).astype(out_dtype)


def int8_matmul(x_q, w_q, w_scale, act_scale, out_dtype=jnp.float32):
    """``x_q [M, K]`` int8 @ ``w_q [K, N]`` int8 with the dequantize
    epilogue ``acc * act_scale * w_scale[n]``. K and N must be 128-
    aligned (see :func:`matmul_shapes_supported`); M is padded by the
    grid. Returns ``[M, N]`` in ``out_dtype``."""
    M, K = x_q.shape
    N = w_q.shape[1]
    if not matmul_shapes_supported(K, N):
        raise ValueError(
            f"int8_matmul needs K % 128 == 0 and N % 128 == 0, got "
            f"K={K}, N={N} (the dispatch layer routes these shapes to "
            f"the XLA fallback)")
    bm = min(_env_block("PTPU_INT8_BLOCK_M", DEFAULT_BLOCK_M), max(8, M))
    bn = _divisor_block(N, _env_block("PTPU_INT8_BLOCK_N", DEFAULT_BLOCK_N))
    bk = _divisor_block(K, _env_block("PTPU_INT8_BLOCK_K", DEFAULT_BLOCK_K))
    act = jnp.reshape(jnp.asarray(act_scale, jnp.float32), (1, 1))
    ws = w_scale.astype(jnp.float32)[None, :]            # [1, N]
    return pl.pallas_call(
        functools.partial(_mm_kernel, out_dtype=out_dtype),
        grid=(pl.cdiv(M, bm), N // bn, K // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(x_q, w_q, ws, act)


# ---------------------------------------------------------------------------
# linear entries
# ---------------------------------------------------------------------------


def _lead2d(x):
    lead = x.shape[:-1]
    return x.reshape((-1, x.shape[-1])), lead


def int8_linear(x, w_q, w_scale, bias=None, act_scale=None):
    """Quantized linear over pre-quantized weights (the serving path:
    ``slim.QuantizedLinear``): activations are quantized per tensor
    (statically via ``act_scale`` or dynamically via absmax), the gemm
    runs int8 end to end, bias adds in the activation dtype. ``x``
    ``[..., K]`` float; returns ``[..., N]`` in x's dtype."""
    x2, lead = _lead2d(x)
    x_q, a_s = quantize_per_tensor(x2, act_scale)
    y = int8_matmul(x_q, w_q, w_scale, a_s, out_dtype=x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y.reshape(lead + (w_q.shape[1],))


@jax.custom_vjp
def _amp_mm(x2, w):
    w_q, w_s = quantize_per_channel(w)
    x_q, a_s = quantize_per_tensor(x2)
    return int8_matmul(x_q, w_q, w_s, a_s, out_dtype=x2.dtype)


def _amp_mm_fwd(x2, w):
    return _amp_mm(x2, w), (x2, w)


def _amp_mm_bwd(res, g):
    x2, w = res
    # straight-through: gradients flow to the UNquantized operands via
    # the dense pair (the master weights stay full precision; the int8
    # rounding is treated as identity, standard QAT practice)
    dx = jnp.matmul(g, w.T.astype(g.dtype)).astype(x2.dtype)
    dw = jnp.matmul(x2.T.astype(g.dtype), g).astype(w.dtype)
    return dx, dw


_amp_mm.defvjp(_amp_mm_fwd, _amp_mm_bwd)


def int8_amp_linear(x, w, bias=None):
    """Flag-gated AMP training matmul (``FLAGS_amp_int8_matmul``): both
    operands dynamically quantized per forward, straight-through dense
    backward. ``w [K, N]`` float parameter."""
    x2, lead = _lead2d(x)
    y = _amp_mm(x2, w)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y.reshape(lead + (w.shape[1],))
