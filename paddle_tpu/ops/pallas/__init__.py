"""paddle_tpu.ops.pallas — the framework's hand-written TPU kernel layer.

The TPU-native replacement for the reference's fused-CUDA operator
library (PAPER.md: the operators/fused/ layer — fused_attention,
softmax_with_cross_entropy, the slim int8 kernels). Every kernel here
follows ONE dispatch convention:

- a ``FLAGS_*`` kill switch (see :func:`kernels` for the flag matrix)
  whose *off* position routes to an XLA fallback that is bit-compatible
  with the pre-kernel implementation;
- TPU-only by default: on other backends the kernel falls back to XLA
  unless ``FLAGS_pallas_interpret`` forces the Pallas interpreter (the
  ``pallas`` pytest marker does this — parity tests run the REAL kernel
  bodies on CPU);
- every fallback is counted: :func:`note_fallback` feeds the
  ``pallas_fallback_total{kernel,reason}`` counter (monitor mode) and
  the always-on :data:`PALLAS_STATS` dict, so ``tools/monitor_report.py
  --kernels`` can show which kernels are live vs degraded;
- a parity test in tests/test_pallas_kernels.py and a bench line in
  ``bench.py --kernels`` (BENCH_kernels.json).

Kernel inventory (docs/PERF_KERNELS.md):

==================  ==========================  =========================
kernel              flag                        XLA fallback
==================  ==========================  =========================
flash_attention     (shape gate in ops.         _sdpa_xla softmax
                    attention, TPU-only)        composition
chunked_ce          FLAGS_pallas_ce             nn.chunked_ce fori_loop
                                                streaming path
paged_decode        FLAGS_pallas_paged_decode   gather_pages + masked
                                                SDPA (models/gpt.py)
int8_matmul         FLAGS_pallas_int8           slim dequant-to-float /
                                                XLA int8 dot
bgmv                FLAGS_pallas_bgmv           XLA adapter gather +
                                                einsum shrink/expand
==================  ==========================  =========================
"""

from __future__ import annotations

import threading
from typing import Dict, List

from ...core.flags import get_flag

__all__ = [
    "flash_attention", "chunked_ce_loss", "paged_decode_attention",
    "paged_decode_attention_quant",
    "int8_matmul", "int8_linear", "int8_amp_linear", "quantize_per_channel",
    "bgmv", "bgmv_xla",
    "kernels", "kernel_enabled", "note_fallback", "backend_supported",
    "PALLAS_STATS", "reset_pallas_stats",
]

#: always-on fallback observability (monitor-independent, like
#: nn.scan.SCAN_STATS): {(kernel, reason): count}
PALLAS_STATS: Dict[tuple, int] = {}
_STATS_LOCK = threading.Lock()

#: the registry rows behind :func:`kernels` — name -> (flag, fallback
#: description). flash_attention predates the flag convention: its gate
#: is the shape/backend check in ops.attention._flash_supported.
_REGISTRY = {
    "flash_attention": (None, "XLA softmax composition (ops.attention."
                              "_sdpa_xla); gate: _flash_supported"),
    "chunked_ce": ("pallas_ce", "pure-XLA fori_loop streaming CE "
                                "(nn.chunked_ce._ce_hard)"),
    "paged_decode": ("pallas_paged_decode", "gather_pages + masked SDPA "
                                            "(models/gpt.py)"),
    "int8_matmul": ("pallas_int8", "weight dequantize-to-float matmul / "
                                   "XLA int8 dot (slim.QuantizedLinear)"),
    "bgmv": ("pallas_bgmv", "XLA adapter gather + einsum shrink/expand "
                            "(ops.pallas.bgmv.bgmv_xla)"),
}


def reset_pallas_stats() -> None:
    with _STATS_LOCK:
        PALLAS_STATS.clear()


def note_fallback(kernel: str, reason: str) -> None:
    """Record that a kernel-eligible call degraded to its XLA fallback.

    Bumps :data:`PALLAS_STATS` always and the
    ``pallas_fallback_total{kernel,reason}`` registry counter in monitor
    mode. Reasons: ``flag_off`` (kill switch), ``cpu_backend`` (non-TPU
    without FLAGS_pallas_interpret), ``shape`` (unsupported geometry,
    e.g. int8 gemm dims not 128-aligned).
    """
    with _STATS_LOCK:
        PALLAS_STATS[(kernel, reason)] = \
            PALLAS_STATS.get((kernel, reason), 0) + 1
    from ...monitor import enabled as _mon_enabled
    if _mon_enabled():
        from ...monitor import get_registry
        get_registry().counter(
            "pallas_fallback_total",
            "ops.pallas kernel calls that degraded to the XLA fallback, "
            "by kernel and cause").inc(kernel=kernel, reason=reason)


def backend_supported() -> bool:
    """True when Pallas kernel bodies can execute here: a real TPU, or
    any backend with the interpreter forced (``FLAGS_pallas_interpret``,
    flipped by the ``pallas`` pytest marker)."""
    import jax
    return (jax.default_backend() == "tpu"
            or bool(get_flag("pallas_interpret")))


def kernel_enabled(name: str, note: bool = True) -> bool:
    """One gate for every kernel call site: flag on AND backend capable.

    ``note=False`` suppresses fallback accounting for probe-style calls
    (``kernels()`` uses it to report status without inflating counters).
    """
    flag, _ = _REGISTRY[name]
    if flag is not None and not get_flag(flag):
        if note:
            note_fallback(name, "flag_off")
        return False
    if not backend_supported():
        if note:
            note_fallback(name, "cpu_backend")
        return False
    return True


def kernels() -> List[dict]:
    """Enumerate the kernel layer: name, kill-switch flag (and its
    current value), whether dispatch would serve the Pallas body right
    now (``live``), the XLA fallback that serves otherwise, and the
    fallback counts observed so far. Consumed by
    ``tools/monitor_report.py --kernels`` and the registry tests."""
    import jax
    rows = []
    for name, (flag, fallback) in _REGISTRY.items():
        if name == "flash_attention":
            live = jax.default_backend() == "tpu"
        else:
            live = kernel_enabled(name, note=False)
        with _STATS_LOCK:
            fb = {k[1]: v for k, v in PALLAS_STATS.items()
                  if k[0] == name}
        rows.append({
            "kernel": name,
            "flag": f"FLAGS_{flag}" if flag else None,
            "flag_value": bool(get_flag(flag)) if flag else None,
            "live": bool(live),
            "fallback": fallback,
            "fallbacks_seen": fb,
        })
    return rows


# -- kernel entry points (lazy imports: pallas/jax.experimental loads
# only when a kernel is actually called) ----------------------------------

def flash_attention(*args, **kw):
    from .flash_attention import flash_attention as _fa
    return _fa(*args, **kw)


def chunked_ce_loss(*args, **kw):
    from .chunked_ce import chunked_ce_loss as _ce
    return _ce(*args, **kw)


def paged_decode_attention(*args, **kw):
    from .paged_decode import paged_decode_attention as _pd
    return _pd(*args, **kw)


def paged_decode_attention_quant(*args, **kw):
    from .paged_decode import paged_decode_attention_quant as _pd
    return _pd(*args, **kw)


def int8_matmul(*args, **kw):
    from .quant_matmul import int8_matmul as _mm
    return _mm(*args, **kw)


def int8_linear(*args, **kw):
    from .quant_matmul import int8_linear as _ln
    return _ln(*args, **kw)


def int8_amp_linear(*args, **kw):
    from .quant_matmul import int8_amp_linear as _al
    return _al(*args, **kw)


def quantize_per_channel(*args, **kw):
    from .quant_matmul import quantize_per_channel as _q
    return _q(*args, **kw)


def bgmv(*args, **kw):
    from .bgmv import bgmv as _b
    return _b(*args, **kw)


def bgmv_xla(*args, **kw):
    from .bgmv import bgmv_xla as _b
    return _b(*args, **kw)
