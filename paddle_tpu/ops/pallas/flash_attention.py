"""Pallas flash attention (TPU).

Tiled online-softmax attention over VMEM blocks; replaces the reference's
fmha CUDA kernels (reference: operators/fused/fused_attention_op.cu).
Custom VJP so the eager tape and jit grads both work.

This file currently exposes the API; the tuned kernel lands with the model
milestone — callers fall back to the XLA composition via ops.attention.
"""

from __future__ import annotations


def flash_attention(q, k, v, causal=False, block_q=128, block_k=128):
    raise NotImplementedError("pallas flash attention kernel pending")
