"""Pallas flash attention (TPU).

Tiled online-softmax attention with a custom VJP; the TPU-native
replacement for the reference's fused CUDA attention stack
(reference: paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
fused_gate_attention_op.cu).

Design (FlashAttention-2 schedule, expressed the Mosaic way). Two kernel
generations share the public entry:

v2 (default, bias-free): consumes q/k/v as [B, S, H*D] — a free bitcast of
the framework layout, so NO transpose ever materializes around the kernel.
A head is a static lane-column slice (two D=64 heads share one 128-lane
block); each program processes `block_b` batch rows x the packed heads,
amortizing per-program pipeline overhead. The backward is ONE fused kernel
(grid q-sweep innermost): the score/dp tiles are computed once, dk/dv
accumulate in block scratch written as each k block completes, and dq
accumulates in a full-Sq f32 scratch flushed once through a
constant-indexed full-sequence output window. delta = rowsum(dO*O) is
computed in-kernel from blocks already in VMEM; lse rides a narrow
[B, H, S, 8] tile.

v1 (fallback: additive [B,1,1,Sk] bias, odd head counts): grid
(B, H, nq, nk) over [B, H, S, D] views with the classic dq/dkv kernel
split.

Common to both: online-softmax forward with running (m, l) scratch,
O(S*D) HBM traffic; causal tiles above the diagonal are compute-skipped
via `pl.when`; in-kernel rematerialized dropout via a stateless
murmur3-finalizer hash over absolute coordinates (the backward REGENERATES
the mask, nothing is stored); MXU compute follows the framework matmul
precision policy with f32 accumulation.

Tests run these same kernels on CPU via the Pallas interpreter.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 names the dataclass TPUCompilerParams; same fields
    pltpu.CompilerParams = pltpu.TPUCompilerParams

# 512x512 tiles win on v5e: fewer grid steps amortize the VMEM loads and the
# p-tile (512*512*4B = 1 MiB) still fits comfortably; measured ~28% faster
# than 128x128 at S=2048 and ahead of XLA's fused sdpa.
DEFAULT_BLOCK = 512
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mxu_dtype(in_dtype) -> jnp.dtype:
    """MXU input dtype: mirror XLA's matmul-precision policy.

    Default policy lowers f32 gemms to bf16 MXU passes (f32 accumulate);
    `tpu_matmul_precision=highest/float32` keeps full f32. The interpreter
    (CPU tests) always computes f32 so parity tolerances stay tight.
    """
    from ...core.flags import matmul_precision
    if _interpret() or matmul_precision() is not None:
        return jnp.float32
    return jnp.bfloat16


def _causal_mask(s, qi, ki, block_q, block_k, off):
    """Bottom-right-aligned causal mask: query row i sees keys j <= i + off
    where off = Sk - Sq (matches _sdpa_xla's tril(k=Sk-Sq) semantics for
    chunked prefill against a longer KV cache)."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows + off >= cols, s, NEG_INF)


def _dropout_keep(seed_ref, b, h, qi, ki, shape, rate):
    """Deterministic keep mask scaled by 1/(1-rate).

    A STATELESS counter-based hash (murmur3 finalizer) over the absolute
    (batch, head, query-row, key-col) coordinates + the step seed: the
    backward kernels RE-GENERATE the identical mask instead of storing S^2
    bits — the dropout analogue of flash's no-residual rematerialization
    (reference's fused attention stores its uint8 mask, fmha_ref.h). A
    pure function of indices is bit-reproducible across the fwd/dq/dkv
    kernels by construction, which Mosaic's stateful hardware PRNG is not.
    """
    bq, bk = shape
    rows = (qi * bq + jax.lax.broadcasted_iota(jnp.int32, shape, 0)) \
        .astype(jnp.uint32)
    cols = (ki * bk + jax.lax.broadcasted_iota(jnp.int32, shape, 1)) \
        .astype(jnp.uint32)
    bh = (b.astype(jnp.uint32) * jnp.uint32(0xAC564B05)
          + h.astype(jnp.uint32) * jnp.uint32(19349663))
    from .rng import fmix32, keep_threshold
    x = fmix32(rows * jnp.uint32(0x9E3779B1)
               ^ cols * jnp.uint32(0x85EBCA6B)
               ^ bh
               ^ seed_ref[0].astype(jnp.uint32)
               ^ (seed_ref[1].astype(jnp.uint32) << 1))
    keep = x >= keep_threshold(rate)
    return keep.astype(jnp.float32) / (1.0 - rate)


def _dot(a, b, dims, cd=jnp.float32):
    """MXU matmul: operands cast to the policy dtype, f32 accumulation."""
    return jax.lax.dot_general(a.astype(cd), b.astype(cd), (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                cd, off, rate):
    b, h = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        s = _dot(q_ref[0, 0], k_ref[0, 0], ((1,), (1,)), cd) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)   # [1, bk] broadcast
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)

        m_prev = m_scr[:, :1]                            # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # fully-masked tile: m_new stays NEG_INF; shift by 0 to avoid inf-inf
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift)                           # [bq, bk]
        if causal:
            p = jnp.where(s == NEG_INF, 0.0, p)
        alpha = jnp.exp(m_prev - shift)                  # [bq, 1] (<= 1)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = p
        if rate > 0.0:
            # dropout on the normalized probs commutes to masking the pv
            # accumulation only; the softmax denominator stays undropped
            pv = p * _dropout_keep(seed_ref, b, h, qi, ki, p.shape, rate)
        acc_scr[:] = acc_scr[:] * alpha + _dot(pv, v_ref[0, 0],
                                               ((1,), (0,)), cd)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)             # all-masked row -> 0
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        if lse_ref is not None:
            m = m_scr[:, :1]
            lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(safe_l))
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref[0, 0].shape)


def _mk_kernel(kern, has_bias, n_in=3, lse_out=True, has_seed=False, **kw):
    """Adapt ref lists: a leading seed_ref when dropout is on, bias_ref=None
    inserted after the n_in inputs when there is no bias input, and
    lse_ref=None after the o output when the lse output is dropped."""
    def wrapped(*refs):
        if has_seed:
            seed_ref, refs = refs[0], refs[1:]
        else:
            seed_ref = None
        n = n_in + (1 if has_bias else 0)
        ins, rest = list(refs[:n]), list(refs[n:])
        if not has_bias:
            ins = ins[:n_in] + [None] + ins[n_in:]
        if not lse_out:
            rest = rest[:1] + [None] + rest[1:]
        return kern(seed_ref, *ins, *rest, **kw)

    return wrapped


def _fwd_v1(q, k, v, bias, scale, causal, block_q, block_k,
            save_residuals=True, seed=None, rate=0.0):
    """q,k,v: [B, H, S, D]. Returns (o, lse[B, H, S, 8]) — the lse rows
    stay in the narrow tile exactly as the kernel wrote them so the backward
    can consume them without an XLA re-broadcast; lse is None when
    save_residuals=False (inference: no lse write, saves S*128 f32 HBM
    traffic per (b, h), mirroring the upstream kernel's save_residuals)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    qs = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    ks = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0))
    in_specs = []
    args = []
    if rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    in_specs += [qs, ks, ks]
    args += [q, k, v]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                     lambda b, h, i, j: (b, 0, 0, j)))
        args.append(bias)
    kern = _mk_kernel(_fwd_kernel, bias is not None, lse_out=save_residuals,
                      has_seed=rate > 0.0, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k,
                      cd=_mxu_dtype(q.dtype), off=Sk - Sq, rate=rate)

    out_specs = [pl.BlockSpec((1, 1, block_q, D),
                              lambda b, h, i, j: (b, h, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype)]
    if save_residuals:
        # row stats ride a narrow 8-lane tile: [B, H, S, 8] is 16x less
        # HBM than a full 128-lane broadcast and Mosaic accepts last-dim 8
        out_specs.append(pl.BlockSpec((1, 1, block_q, 8),
                                      lambda b, h, i, j: (b, h, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B, H, Sq, 8), jnp.float32))

    out = pl.pallas_call(
        kern,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 8), jnp.float32),
            pltpu.VMEM((block_q, 8), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*args)
    if save_residuals:
        o, lse = out
        return o, lse
    return out[0], None


# ---------------------------------------------------------------------------
# v2 kernels: native [B, S, H*D] layout, batched programs, fused backward
#
# The v1 kernels grid over every (batch, head) pair — for BERT-base shapes
# that is 576 programs of ~2 µs work each, and the [B,S,H,D]->[B,H,S,D]
# relayout XLA must materialize around them costs more HBM than the
# attention itself. v2 instead:
#   - consumes q/k/v as [B, S, E] (a free bitcast of the framework layout):
#     a head is a static lane-column slice, two D=64 heads share one
#     128-lane block, so no transpose ever materializes;
#   - processes `block_b` batch rows x `hp` heads per program, amortizing
#     the per-program pipeline overhead;
#   - fuses the whole backward into ONE kernel producing dq/dk/dv in a
#     single pass: the score and dp tiles are computed once (the v1 dq/dkv
#     split computes them twice) with dk/dv accumulated across q-blocks in
#     a full-S VMEM scratch.
# Bias is not supported here (the padded-batch case routes to v1).
# ---------------------------------------------------------------------------


def _heads_per_block(D: int, H: int):
    """Lane width of one kernel column block and the heads packed in it."""
    if D % 128 == 0:
        return 1, D
    if D == 64 and H % 2 == 0:
        return 2, 128
    return None, None


def _fwd2_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                 m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                 cd, off, rate, bb, hp, D):
    bg, hg = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        for bi in range(bb):
            for hh in range(hp):
                q = q_ref[bi, :, hh * D:(hh + 1) * D]
                k = k_ref[bi, :, hh * D:(hh + 1) * D]
                v = v_ref[bi, :, hh * D:(hh + 1) * D]
                s = _dot(q, k, ((1,), (1,)), cd) * scale
                if causal:
                    s = _causal_mask(s, qi, ki, block_q, block_k, off)
                m_prev = m_scr[bi, hh][:, :1]
                m_new = jnp.maximum(m_prev,
                                    jnp.max(s, axis=1, keepdims=True))
                shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
                p = jnp.exp(s - shift)
                if causal:
                    p = jnp.where(s == NEG_INF, 0.0, p)
                alpha = jnp.exp(m_prev - shift)
                l_new = alpha * l_scr[bi, hh][:, :1] \
                    + jnp.sum(p, axis=1, keepdims=True)
                pv = p
                if rate > 0.0:
                    b_abs = bg * bb + bi
                    h_abs = hg * hp + hh
                    pv = p * _dropout_keep(seed_ref, b_abs, h_abs, qi, ki,
                                           p.shape, rate)
                acc_scr[bi, hh] = acc_scr[bi, hh] * alpha \
                    + _dot(pv, v, ((1,), (0,)), cd)
                m_scr[bi, hh] = jnp.broadcast_to(m_new, m_scr[bi, hh].shape)
                l_scr[bi, hh] = jnp.broadcast_to(l_new, l_scr[bi, hh].shape)

    @pl.when(ki == nk - 1)
    def _finish():
        for bi in range(bb):
            outs = []
            for hh in range(hp):
                l = l_scr[bi, hh][:, :1]
                safe_l = jnp.where(l == 0.0, 1.0, l)
                outs.append(acc_scr[bi, hh] / safe_l)
                if lse_ref is not None:
                    m = m_scr[bi, hh][:, :1]
                    lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(safe_l))
                    lse_ref[bi, hh] = jnp.broadcast_to(
                        lse, lse_ref[bi, hh].shape)
            o_ref[bi] = jnp.concatenate(outs, axis=1).astype(o_ref.dtype)


def _fwd2(q, k, v, scale, causal, block_q, block_k, hp, width,
          save_residuals=True, seed=None, rate=0.0, block_b=4):
    """q,k,v: [B, S, E]. Returns (o [B,S,E], lse [B,H,Sq,8] or None)."""
    B, Sq, E = q.shape
    Sk = k.shape[1]
    D = width // hp
    H = E // D
    nq, nk = Sq // block_q, Sk // block_k
    while B % block_b:
        block_b //= 2
    bb = max(block_b, 1)

    qs = pl.BlockSpec((bb, block_q, width), lambda b, h, i, j: (b, i, h))
    ks = pl.BlockSpec((bb, block_k, width), lambda b, h, i, j: (b, j, h))
    in_specs = []
    args = []
    if rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    in_specs += [qs, ks, ks]
    args += [q, k, v]

    def kern(*refs):
        if rate > 0.0:
            seed_ref, refs = refs[0], refs[1:]
        else:
            seed_ref = None
        if save_residuals:
            q_r, k_r, v_r, o_r, lse_r, m_s, l_s, a_s = refs
        else:
            q_r, k_r, v_r, o_r, m_s, l_s, a_s = refs
            lse_r = None
        return _fwd2_kernel(seed_ref, q_r, k_r, v_r, o_r, lse_r, m_s, l_s,
                            a_s, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            cd=_mxu_dtype(q.dtype), off=Sk - Sq, rate=rate,
                            bb=bb, hp=hp, D=D)

    out_specs = [pl.BlockSpec((bb, block_q, width),
                              lambda b, h, i, j: (b, i, h))]
    out_shape = [jax.ShapeDtypeStruct((B, Sq, E), q.dtype)]
    if save_residuals:
        out_specs.append(pl.BlockSpec((bb, hp, block_q, 8),
                                      lambda b, h, i, j: (b, h, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B, H, Sq, 8), jnp.float32))

    out = pl.pallas_call(
        kern,
        grid=(B // bb, H // hp, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bb, hp, block_q, 8), jnp.float32),
            pltpu.VMEM((bb, hp, block_q, 8), jnp.float32),
            pltpu.VMEM((bb, hp, block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*args)
    if save_residuals:
        return out[0], out[1]
    return out[0], None


def _bwd2_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                 dq_ref, dk_ref, dv_ref, dq_scr, dk_scr, dv_scr, *,
                 scale, causal, block_q, block_k, cd, off, rate, bb, hp, D):
    """Fused backward: grid (B/bb, H/hp, nk, nq) with the q sweep innermost.

    dk/dv accumulate across the inner q sweep in block-sized scratch and
    are written at qi == nq-1 (their output block index is the OUTER ki,
    stable across the sweep, so the window flushes exactly once). dq
    accumulates across the whole (ki, qi) sweep in a full-Sq scratch; its
    output window spans the full sequence with a constant index per
    (b, h) program set and is written once at the final step."""
    bg, hg = pl.program_id(0), pl.program_id(1)
    ki, qi = pl.program_id(2), pl.program_id(3)
    nk, nq = pl.num_programs(2), pl.num_programs(3)

    @pl.when((ki == 0) & (qi == 0))
    def _init_dq():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        for bi in range(bb):
            for hh in range(hp):
                sl = slice(hh * D, (hh + 1) * D)
                q = q_ref[bi, :, sl]
                k = k_ref[bi, :, sl]
                v = v_ref[bi, :, sl]
                do = do_ref[bi, :, sl]
                o = o_ref[bi, :, sl]
                lse = lse_ref[bi, hh][:, :1]
                delta = jnp.sum(do.astype(jnp.float32)
                                * o.astype(jnp.float32),
                                axis=1, keepdims=True)
                s = _dot(q, k, ((1,), (1,)), cd) * scale
                if causal:
                    s = _causal_mask(s, qi, ki, block_q, block_k, off)
                p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))
                dp = _dot(do, v, ((1,), (1,)), cd)
                pv = p
                if rate > 0.0:
                    b_abs = bg * bb + bi
                    h_abs = hg * hp + hh
                    keepf = _dropout_keep(seed_ref, b_abs, h_abs, qi, ki,
                                          p.shape, rate)
                    pv = p * keepf
                    dp = dp * keepf
                ds = p * (dp - delta) * scale
                rows = pl.ds(qi * block_q, block_q)
                dq_scr[bi, hh, rows] += _dot(ds, k, ((1,), (0,)), cd)
                dk_scr[bi, hh] += _dot(ds, q, ((0,), (0,)), cd)
                dv_scr[bi, hh] += _dot(pv, do, ((0,), (0,)), cd)

    @pl.when(qi == nq - 1)
    def _write_dkv():
        for bi in range(bb):
            dk_ref[bi] = jnp.concatenate(
                [dk_scr[bi, hh] for hh in range(hp)],
                axis=1).astype(dk_ref.dtype)
            dv_ref[bi] = jnp.concatenate(
                [dv_scr[bi, hh] for hh in range(hp)],
                axis=1).astype(dv_ref.dtype)

    @pl.when((ki == nk - 1) & (qi == nq - 1))
    def _write_dq():
        for bi in range(bb):
            dq_ref[bi] = jnp.concatenate(
                [dq_scr[bi, hh] for hh in range(hp)],
                axis=1).astype(dq_ref.dtype)


def _bwd2(q, k, v, o, lse, do, scale, causal, block_q, block_k, hp, width,
          seed=None, rate=0.0, block_b=2):
    """q,k,v,o,do: [B, S, E]; lse: [B, H, Sq, 8]. Returns dq, dk, dv."""
    B, Sq, E = q.shape
    Sk = k.shape[1]
    D = width // hp
    H = E // D
    nq, nk = Sq // block_q, Sk // block_k
    while B % block_b:
        block_b //= 2
    bb = max(block_b, 1)

    qs = pl.BlockSpec((bb, block_q, width), lambda b, h, j, i: (b, i, h))
    ks = pl.BlockSpec((bb, block_k, width), lambda b, h, j, i: (b, j, h))
    rowq = pl.BlockSpec((bb, hp, block_q, 8),
                        lambda b, h, j, i: (b, h, i, 0))
    in_specs = []
    args = []
    if rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    in_specs += [qs, ks, ks, qs, qs, rowq]
    args += [q, k, v, do, o, lse]

    def kern(*refs):
        if rate > 0.0:
            seed_ref, refs = refs[0], refs[1:]
        else:
            seed_ref = None
        return _bwd2_kernel(seed_ref, *refs, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            cd=_mxu_dtype(q.dtype), off=Sk - Sq, rate=rate,
                            bb=bb, hp=hp, D=D)

    dq, dk, dv = pl.pallas_call(
        kern,
        grid=(B // bb, H // hp, nk, nq),
        in_specs=in_specs,
        out_specs=[
            # dq: one full-sequence window per (b, h) program set — the
            # index is constant over the (ki, qi) sweep so it flushes
            # exactly once, after the final accumulation step
            pl.BlockSpec((bb, Sq, width), lambda b, h, j, i: (b, 0, h)),
            pl.BlockSpec((bb, block_k, width), lambda b, h, j, i: (b, j, h)),
            pl.BlockSpec((bb, block_k, width), lambda b, h, j, i: (b, j, h)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sq, E), q.dtype),
            jax.ShapeDtypeStruct((B, Sk, E), k.dtype),
            jax.ShapeDtypeStruct((B, Sk, E), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bb, hp, Sq, D), jnp.float32),
            pltpu.VMEM((bb, hp, block_k, D), jnp.float32),
            pltpu.VMEM((bb, hp, block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, o_ref,
               lse_ref, dq_ref, acc_scr, *, scale, causal, block_q,
               block_k, cd, off, rate):
    b, h = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        lse = lse_ref[0, 0][:, :1]                       # [bq, 1]
        # delta = rowsum(dO * O), recomputed from the blocks already in
        # VMEM (D is small) — cheaper than an XLA precompute that writes
        # and lane-broadcasts a [B, H, S, 128] array through HBM
        delta = jnp.sum(do_ref[0, 0].astype(jnp.float32)
                        * o_ref[0, 0].astype(jnp.float32),
                        axis=1, keepdims=True)           # [bq, 1]
        s = _dot(q_ref[0, 0], k_ref[0, 0], ((1,), (1,)), cd) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        # fully-masked row (lse = NEG_INF): shift by 0 so exp(-1e30) -> 0
        p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))  # [bq, bk]
        dp = _dot(do_ref[0, 0], v_ref[0, 0], ((1,), (1,)), cd)
        if rate > 0.0:
            dp = dp * _dropout_keep(seed_ref, b, h, qi, ki, p.shape, rate)
        ds = p * (dp - delta) * scale
        acc_scr[:] += _dot(ds, k_ref[0, 0], ((1,), (0,)), cd)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, o_ref,
                lse_ref, dk_ref, dv_ref, db_ref, dk_scr, dv_scr, db_scr, *,
                scale, causal, block_q, block_k, cd, off, rate):
    b, h = pl.program_id(0), pl.program_id(1)
    ki, qi = pl.program_id(2), pl.program_id(3)          # k outer, q inner
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if db_scr is not None:
            db_scr[:] = jnp.zeros_like(db_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        lse = lse_ref[0, 0][:, :1]
        delta = jnp.sum(do_ref[0, 0].astype(jnp.float32)
                        * o_ref[0, 0].astype(jnp.float32),
                        axis=1, keepdims=True)           # [bq, 1]
        s = _dot(q_ref[0, 0], k_ref[0, 0], ((1,), (1,)), cd) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        # fully-masked row (lse = NEG_INF): shift by 0 so exp(-1e30) -> 0
        p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))  # [bq, bk]
        pv = p
        dp = _dot(do_ref[0, 0], v_ref[0, 0], ((1,), (1,)), cd)
        if rate > 0.0:
            # same (b, h, qi, ki) fold as the forward: identical mask
            keepf = _dropout_keep(seed_ref, b, h, qi, ki, p.shape, rate)
            pv = p * keepf
            dp = dp * keepf
        dv_scr[:] += _dot(pv, do_ref[0, 0], ((0,), (0,)), cd)  # p~^T dO
        ds = p * (dp - delta) * scale
        dk_scr[:] += _dot(ds, q_ref[0, 0], ((0,), (0,)), cd)  # ds^T q
        if db_scr is not None:
            # d(bias): ds summed over query rows (scale undone: bias adds to
            # the raw scores AFTER the q@k scaling)
            db_scr[:1] += jnp.sum(ds / scale, axis=0, keepdims=True)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)
        if db_ref is not None:
            db_ref[0, 0] = db_scr[:1].astype(db_ref.dtype)


def _mk_dkv_kernel(has_bias, has_seed=False, **kw):
    def wrapped(*refs):
        if has_seed:
            seed_ref, refs = refs[0], refs[1:]
        else:
            seed_ref = None
        if has_bias:
            return _dkv_kernel(seed_ref, *refs, **kw)
        q, k, v, do, o, lse, dk, dv, dk_scr, dv_scr = refs
        return _dkv_kernel(seed_ref, q, k, v, None, do, o, lse, dk, dv,
                           None, dk_scr, dv_scr, None, **kw)

    return wrapped


def _bwd_v1(q, k, v, bias, o, lse, do, scale, causal, block_q, block_k,
            seed=None, rate=0.0):
    """lse arrives as the forward's [B, H, Sq, 8] narrow-tile output
    and is fed straight to the kernels; delta = rowsum(dO*O) is computed
    in-kernel from the dO/O blocks (no XLA precompute, no HBM round-trip
    for either per-row vector)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    qs = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    ks_j = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0))
    rowq = pl.BlockSpec((1, 1, block_q, 8), lambda b, h, i, j: (b, h, i, 0))

    seed_specs = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  if rate > 0.0 else [])
    seed_args = [seed] if rate > 0.0 else []
    dq_in_specs = seed_specs + [qs, ks_j, ks_j]
    dq_args = seed_args + [q, k, v]
    if bias is not None:
        dq_in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                        lambda b, h, i, j: (b, 0, 0, j)))
        dq_args.append(bias)
    dq_in_specs += [qs, qs, rowq]
    dq_args += [do, o, lse]

    dq = pl.pallas_call(
        _mk_kernel(_dq_kernel, bias is not None, has_seed=rate > 0.0,
                   scale=scale, causal=causal, block_q=block_q,
                   block_k=block_k, cd=_mxu_dtype(q.dtype), off=Sk - Sq,
                   rate=rate),
        grid=(B, H, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*dq_args)

    # dkv: grid (B, H, nk, nq) — i indexes k blocks, j indexes q blocks
    qs_j = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, j, 0))
    ks_i = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0))
    rowq_j = pl.BlockSpec((1, 1, block_q, 8),
                          lambda b, h, i, j: (b, h, j, 0))
    dkv_in_specs = seed_specs + [qs_j, ks_i, ks_i]
    dkv_args = seed_args + [q, k, v]
    if bias is not None:
        dkv_in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                         lambda b, h, i, j: (b, 0, 0, i)))
        dkv_args.append(bias)
    dkv_in_specs += [qs_j, qs_j, rowq_j]
    dkv_args += [do, o, lse]

    dkv_out_specs = [
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0)),
    ]
    dkv_out_shape = [
        jax.ShapeDtypeStruct(k.shape, k.dtype),
        jax.ShapeDtypeStruct(v.shape, v.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, D), jnp.float32),
        pltpu.VMEM((block_k, D), jnp.float32),
    ]
    if bias is not None:
        # per-(b, h) bias gradient rows; summed over heads below
        dkv_out_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                          lambda b, h, i, j: (b, h, 0, i)))
        dkv_out_shape.append(
            jax.ShapeDtypeStruct((B, H, 1, Sk), jnp.float32))
        dkv_scratch.append(pltpu.VMEM((8, block_k), jnp.float32))

    outs = pl.pallas_call(
        _mk_dkv_kernel(bias is not None, has_seed=rate > 0.0, scale=scale,
                       causal=causal, block_q=block_q, block_k=block_k,
                       cd=_mxu_dtype(q.dtype), off=Sk - Sq, rate=rate),
        grid=(B, H, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shape,
        scratch_shapes=dkv_scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*dkv_args)
    if bias is not None:
        dk, dv, db_h = outs
        db = jnp.sum(db_h, axis=1, keepdims=True)        # [B, 1, 1, Sk]
        return dq, dk, dv, db
    dk, dv = outs
    return dq, dk, dv, None


# ---------------------------------------------------------------------------
# routing + public entry (custom VJP over [B, S, H, D])
# ---------------------------------------------------------------------------


def _seed_arr(seed_f):
    """f32-bitcast seed words back to int32 (seed travels as a float arg so
    the custom_vjp can hand back a plain zero cotangent)."""
    return jax.lax.bitcast_convert_type(seed_f, jnp.int32)


# VMEM budgets (bytes) for picking how many batch rows one v2 program
# processes: the unrolled (bi, hh) loop keeps ~1 score tile live per
# iteration in the forward and ~3 (s/dp/ds) in the backward, and the fused
# backward additionally carries a full-Sq f32 dq scratch. The TPU scoped
# vmem limit is 16M; stay well under it.
_V2_FWD_TILE_BUDGET = 4 * 1024 * 1024
_V2_BWD_TILE_BUDGET = 8 * 1024 * 1024
# the fused backward carries a full-Sq f32 dq scratch AND a full-Sq dq
# output window; beyond this they crowd out the score tiles (measured:
# S=8192/D=64/hp=2 overflows the 16M scoped limit), so longer sequences
# route to the v1 split kernels, which tile everything
_V2_SCRATCH_CAP = 2 * 1024 * 1024


def _v2_plan(q, bias, block_q, block_k):
    """(hp, width, bb_fwd, bb_bwd) when the v2 layout-native kernels
    apply; None routes to v1."""
    B, Sq, H, D = q.shape
    if bias is not None:
        return None
    hp, width = _heads_per_block(D, H)
    if hp is None:
        return None
    tile = block_q * block_k * 4

    def pick(budget_tiles, scratch_per_b):
        bb = 8
        while bb > 1 and (B % bb or bb * hp * tile > budget_tiles
                          or bb * scratch_per_b > _V2_SCRATCH_CAP):
            bb //= 2
        return bb

    bb_fwd = pick(_V2_FWD_TILE_BUDGET, 0)
    bb_bwd = pick(_V2_BWD_TILE_BUDGET // 3, hp * Sq * D * 4)
    if hp * Sq * D * 4 > _V2_SCRATCH_CAP:
        return None
    return hp, width, bb_fwd, bb_bwd


def _fwd(q, k, v, bias, scale, causal, block_q, block_k,
         save_residuals=True, seed=None, rate=0.0):
    """Route [B, S, H, D] inputs to the layout-native v2 kernels (no
    transpose materializes) or the v1 [B, H, S, D] kernels (bias case)."""
    plan = _v2_plan(q, bias, block_q, block_k)
    if plan is not None:
        hp, width, bb_fwd, _ = plan
        B, Sq, H, D = q.shape
        E = H * D
        o, lse = _fwd2(q.reshape(B, Sq, E), k.reshape(B, k.shape[1], E),
                       v.reshape(B, v.shape[1], E), scale, causal, block_q,
                       block_k, hp, width, save_residuals=save_residuals,
                       seed=seed, rate=rate, block_b=bb_fwd)
        return o.reshape(q.shape), lse
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    o, lse = _fwd_v1(qt, kt, vt, bias, scale, causal, block_q, block_k,
                     save_residuals=save_residuals, seed=seed, rate=rate)
    return jnp.swapaxes(o, 1, 2), lse


def _bwd_impl(q, k, v, bias, o, lse, do, scale, causal, block_q, block_k,
              seed=None, rate=0.0):
    plan = _v2_plan(q, bias, block_q, block_k)
    if plan is not None:
        hp, width, _, bb_bwd = plan
        B, Sq, H, D = q.shape
        E = H * D
        r3 = lambda x: x.reshape(B, x.shape[1], E)
        dq, dk, dv = _bwd2(r3(q), r3(k), r3(v), r3(o), lse, r3(do), scale,
                           causal, block_q, block_k, hp, width, seed=seed,
                           rate=rate, block_b=bb_bwd)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape), None)
    qt, kt, vt, ot, dot_ = (jnp.swapaxes(x, 1, 2)
                            for x in (q, k, v, o, do))
    dq, dk, dv, db = _bwd_v1(qt, kt, vt, bias, ot, lse, dot_, scale, causal,
                             block_q, block_k, seed=seed, rate=rate)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2), db)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, bias, seed_f, scale, causal, block_q, block_k, rate):
    o, _ = _fwd(q, k, v, bias, scale, causal, block_q, block_k,
                save_residuals=False, seed=_seed_arr(seed_f), rate=rate)
    return o


def _flash_fwd(q, k, v, bias, seed_f, scale, causal, block_q, block_k,
               rate):
    o, lse = _fwd(q, k, v, bias, scale, causal, block_q, block_k,
                  seed=_seed_arr(seed_f), rate=rate)
    return o, (q, k, v, bias, seed_f, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, rate, res, do):
    q, k, v, bias, seed_f, o, lse = res
    dq, dk, dv, db = _bwd_impl(q, k, v, bias, o, lse, do, scale, causal,
                               block_q, block_k, seed=_seed_arr(seed_f),
                               rate=rate)
    if bias is not None:
        db = db.astype(bias.dtype)
    return dq, dk, dv, db, jnp.zeros_like(seed_f)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _env_block(var: str, default: int) -> int:
    """Validated block-size override from the environment; the error names
    the env var so a bad value is traceable to its source (a bare int()
    ValueError at every flash call gave no hint an env var was the cause)."""
    import os
    raw = os.environ.get(var)
    if not raw:
        return default
    try:
        b = int(raw)
    except ValueError:
        raise ValueError(
            f"{var}={raw!r}: the flash-attention block override must be an "
            f"integer number of rows (a multiple of 128)") from None
    if b <= 0 or b % 128:
        raise ValueError(
            f"{var}={b}: the flash-attention block override must be a "
            f"positive multiple of 128 (the TPU lane tile)")
    return b


def _pick_block(seq_len: int, requested: int) -> int:
    """Largest multiple of 128 that divides seq_len, capped at `requested`
    (so 768 -> 384 with the 512 default rather than failing)."""
    if seq_len % 128:
        raise ValueError(f"flash attention needs seq_len % 128 == 0, "
                         f"got {seq_len}")
    start = (min(requested, seq_len) // 128) * 128
    for b in range(start, 127, -128):
        if seq_len % b == 0:
            return b
    return 128


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK,
                    dropout_rate: float = 0.0, dropout_key=None):
    """Flash attention over [B, S, H, D] inputs (framework layout).

    bias: optional additive mask broadcastable to [B, 1, 1, Sk]
    (e.g. key padding: 0 keep, -1e30 masked).
    dropout_rate/dropout_key: in-kernel attention dropout via a stateless
    counter-based hash (works on TPU and in the interpreter); masks are
    regenerated from the seed in the backward, nothing is stored.
    Returns [B, S, H, D].
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    # tuning override without touching call sites (block sweeps on real
    # hardware; see docs/PERF_GPT.md). Only applied when the caller left
    # the block size at its default — an explicit block_q/block_k argument
    # always wins over the environment.
    if block_q == DEFAULT_BLOCK:
        block_q = _env_block("PTPU_FLASH_BLOCK_Q", block_q)
    if block_k == DEFAULT_BLOCK:
        block_k = _env_block("PTPU_FLASH_BLOCK_K", block_k)
    block_q = _pick_block(Sq, block_q)
    block_k = _pick_block(Sk, block_k)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if bias is not None:
        bias = jnp.broadcast_to(jnp.asarray(bias, jnp.float32),
                                (B, 1, 1, Sk))
    rate = float(dropout_rate)
    if rate >= 1.0:
        # everything dropped: defined all-zeros output (matches the XLA
        # composition); avoids 0/0 from the 1/(1-rate) scaling
        return jnp.zeros_like(q)
    if rate > 0.0:
        if dropout_key is None:
            raise ValueError("dropout_rate > 0 needs dropout_key")
        words = jax.random.key_data(dropout_key).ravel()[:2]
        seed_f = jax.lax.bitcast_convert_type(
            words.astype(jnp.uint32), jnp.float32)
    else:
        seed_f = jnp.zeros((2,), jnp.float32)
    return _flash(q, k, v, bias, seed_f, float(scale), bool(causal),
                  int(block_q), int(block_k), rate)
