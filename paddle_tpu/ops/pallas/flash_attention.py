"""Pallas flash attention (TPU).

Tiled online-softmax attention with a custom VJP; the TPU-native
replacement for the reference's fused CUDA attention stack
(reference: paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
fused_gate_attention_op.cu).

Design (FlashAttention-2 schedule, expressed the Mosaic way):

- forward: grid (B, H, num_q_blocks, num_k_blocks), the k dimension is the
  innermost ("arbitrary") loop; running max `m`, normalizer `l` and the
  unnormalized accumulator live in VMEM scratch that persists across the k
  steps. At the last k step the output block and the logsumexp row are
  written. Only O(block_q x block_k) score tiles ever materialize — HBM
  traffic is O(S*D), not O(S^2).
- backward: `delta = rowsum(dO * O)` precomputed in XLA, then two kernels:
  dq (q outer, k inner) and dkv (k outer, q inner) that rematerialize the
  probability tile from (q, k, lse) — no S^2 residuals are saved.
- causal: score tiles strictly above the diagonal are skipped via
  `pl.when` on the block indices (compute-skip; the grid stays rectangular).
- bias: an optional additive bias broadcastable to [B, 1, 1, Sk]
  (key-padding mask, the BERT case) is added to the score tile.

Inputs are [B, S, H, D] (the framework-wide attention layout); the kernel
grid iterates (B, H) so arrays are viewed [B, H, S, D] internally. Compute
is f32 on the MXU regardless of input dtype; outputs cast back.

Tests run these same kernels on CPU via the Pallas interpreter.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512x512 tiles win on v5e: fewer grid steps amortize the VMEM loads and the
# p-tile (512*512*4B = 1 MiB) still fits comfortably; measured ~28% faster
# than 128x128 at S=2048 and ahead of XLA's fused sdpa.
DEFAULT_BLOCK = 512
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mxu_dtype(in_dtype) -> jnp.dtype:
    """MXU input dtype: mirror XLA's matmul-precision policy.

    Default policy lowers f32 gemms to bf16 MXU passes (f32 accumulate);
    `tpu_matmul_precision=highest/float32` keeps full f32. The interpreter
    (CPU tests) always computes f32 so parity tolerances stay tight.
    """
    from ...core.flags import matmul_precision
    if _interpret() or matmul_precision() is not None:
        return jnp.float32
    return jnp.bfloat16


def _causal_mask(s, qi, ki, block_q, block_k, off):
    """Bottom-right-aligned causal mask: query row i sees keys j <= i + off
    where off = Sk - Sq (matches _sdpa_xla's tril(k=Sk-Sq) semantics for
    chunked prefill against a longer KV cache)."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows + off >= cols, s, NEG_INF)


def _dropout_keep(seed_ref, b, h, qi, ki, shape, rate):
    """Deterministic keep mask scaled by 1/(1-rate).

    A STATELESS counter-based hash (murmur3 finalizer) over the absolute
    (batch, head, query-row, key-col) coordinates + the step seed: the
    backward kernels RE-GENERATE the identical mask instead of storing S^2
    bits — the dropout analogue of flash's no-residual rematerialization
    (reference's fused attention stores its uint8 mask, fmha_ref.h). A
    pure function of indices is bit-reproducible across the fwd/dq/dkv
    kernels by construction, which Mosaic's stateful hardware PRNG is not.
    """
    bq, bk = shape
    rows = (qi * bq + jax.lax.broadcasted_iota(jnp.int32, shape, 0)) \
        .astype(jnp.uint32)
    cols = (ki * bk + jax.lax.broadcasted_iota(jnp.int32, shape, 1)) \
        .astype(jnp.uint32)
    bh = (b.astype(jnp.uint32) * jnp.uint32(0xAC564B05)
          + h.astype(jnp.uint32) * jnp.uint32(19349663))
    x = (rows * jnp.uint32(0x9E3779B1)
         ^ cols * jnp.uint32(0x85EBCA6B)
         ^ bh
         ^ seed_ref[0].astype(jnp.uint32)
         ^ (seed_ref[1].astype(jnp.uint32) << 1))
    # murmur3 fmix32
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    thresh = jnp.uint32(min(rate, 0.999999) * 4294967296.0)
    keep = x >= thresh
    return keep.astype(jnp.float32) / (1.0 - rate)


def _dot(a, b, dims, cd=jnp.float32):
    """MXU matmul: operands cast to the policy dtype, f32 accumulation."""
    return jax.lax.dot_general(a.astype(cd), b.astype(cd), (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                cd, off, rate):
    b, h = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        s = _dot(q_ref[0, 0], k_ref[0, 0], ((1,), (1,)), cd) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)   # [1, bk] broadcast
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)

        m_prev = m_scr[:, :1]                            # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # fully-masked tile: m_new stays NEG_INF; shift by 0 to avoid inf-inf
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift)                           # [bq, bk]
        if causal:
            p = jnp.where(s == NEG_INF, 0.0, p)
        alpha = jnp.exp(m_prev - shift)                  # [bq, 1] (<= 1)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = p
        if rate > 0.0:
            # dropout on the normalized probs commutes to masking the pv
            # accumulation only; the softmax denominator stays undropped
            pv = p * _dropout_keep(seed_ref, b, h, qi, ki, p.shape, rate)
        acc_scr[:] = acc_scr[:] * alpha + _dot(pv, v_ref[0, 0],
                                               ((1,), (0,)), cd)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)             # all-masked row -> 0
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        if lse_ref is not None:
            m = m_scr[:, :1]
            lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(safe_l))
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref[0, 0].shape)


def _mk_kernel(kern, has_bias, n_in=3, lse_out=True, has_seed=False, **kw):
    """Adapt ref lists: a leading seed_ref when dropout is on, bias_ref=None
    inserted after the n_in inputs when there is no bias input, and
    lse_ref=None after the o output when the lse output is dropped."""
    def wrapped(*refs):
        if has_seed:
            seed_ref, refs = refs[0], refs[1:]
        else:
            seed_ref = None
        n = n_in + (1 if has_bias else 0)
        ins, rest = list(refs[:n]), list(refs[n:])
        if not has_bias:
            ins = ins[:n_in] + [None] + ins[n_in:]
        if not lse_out:
            rest = rest[:1] + [None] + rest[1:]
        return kern(seed_ref, *ins, *rest, **kw)

    return wrapped


def _fwd(q, k, v, bias, scale, causal, block_q, block_k,
         save_residuals=True, seed=None, rate=0.0):
    """q,k,v: [B, H, S, D]. Returns (o, lse[B, H, S]) — lse is None when
    save_residuals=False (inference: no lse write, saves S*128 f32 HBM
    traffic per (b, h), mirroring the upstream kernel's save_residuals)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    qs = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    ks = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0))
    in_specs = []
    args = []
    if rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    in_specs += [qs, ks, ks]
    args += [q, k, v]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                     lambda b, h, i, j: (b, 0, 0, j)))
        args.append(bias)
    kern = _mk_kernel(_fwd_kernel, bias is not None, lse_out=save_residuals,
                      has_seed=rate > 0.0, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k,
                      cd=_mxu_dtype(q.dtype), off=Sk - Sq, rate=rate)

    out_specs = [pl.BlockSpec((1, 1, block_q, D),
                              lambda b, h, i, j: (b, h, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype)]
    if save_residuals:
        out_specs.append(pl.BlockSpec((1, 1, block_q, 128),
                                      lambda b, h, i, j: (b, h, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B, H, Sq, 128), jnp.float32))

    out = pl.pallas_call(
        kern,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*args)
    if save_residuals:
        o, lse = out
        return o, lse[:, :, :, 0]
    return out[0], None


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
               dlt_ref, dq_ref, acc_scr, *, scale, causal, block_q,
               block_k, cd, off, rate):
    b, h = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        lse = lse_ref[0, 0][:, :1]                       # [bq, 1]
        delta = dlt_ref[0, 0][:, :1]
        s = _dot(q_ref[0, 0], k_ref[0, 0], ((1,), (1,)), cd) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        # fully-masked row (lse = NEG_INF): shift by 0 so exp(-1e30) -> 0
        p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))  # [bq, bk]
        dp = _dot(do_ref[0, 0], v_ref[0, 0], ((1,), (1,)), cd)
        if rate > 0.0:
            dp = dp * _dropout_keep(seed_ref, b, h, qi, ki, p.shape, rate)
        ds = p * (dp - delta) * scale
        acc_scr[:] += _dot(ds, k_ref[0, 0], ((1,), (0,)), cd)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                dlt_ref, dk_ref, dv_ref, db_ref, dk_scr, dv_scr, db_scr, *,
                scale, causal, block_q, block_k, cd, off, rate):
    b, h = pl.program_id(0), pl.program_id(1)
    ki, qi = pl.program_id(2), pl.program_id(3)          # k outer, q inner
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if db_scr is not None:
            db_scr[:] = jnp.zeros_like(db_scr)

    run = ((qi * block_q + block_q - 1 + off >= ki * block_k)
           if causal else True)

    @pl.when(run)
    def _step():
        lse = lse_ref[0, 0][:, :1]
        delta = dlt_ref[0, 0][:, :1]
        s = _dot(q_ref[0, 0], k_ref[0, 0], ((1,), (1,)), cd) * scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, off)
        # fully-masked row (lse = NEG_INF): shift by 0 so exp(-1e30) -> 0
        p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))  # [bq, bk]
        pv = p
        dp = _dot(do_ref[0, 0], v_ref[0, 0], ((1,), (1,)), cd)
        if rate > 0.0:
            # same (b, h, qi, ki) fold as the forward: identical mask
            keepf = _dropout_keep(seed_ref, b, h, qi, ki, p.shape, rate)
            pv = p * keepf
            dp = dp * keepf
        dv_scr[:] += _dot(pv, do_ref[0, 0], ((0,), (0,)), cd)  # p~^T dO
        ds = p * (dp - delta) * scale
        dk_scr[:] += _dot(ds, q_ref[0, 0], ((0,), (0,)), cd)  # ds^T q
        if db_scr is not None:
            # d(bias): ds summed over query rows (scale undone: bias adds to
            # the raw scores AFTER the q@k scaling)
            db_scr[:1] += jnp.sum(ds / scale, axis=0, keepdims=True)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)
        if db_ref is not None:
            db_ref[0, 0] = db_scr[:1].astype(db_ref.dtype)


def _mk_dkv_kernel(has_bias, has_seed=False, **kw):
    def wrapped(*refs):
        if has_seed:
            seed_ref, refs = refs[0], refs[1:]
        else:
            seed_ref = None
        if has_bias:
            return _dkv_kernel(seed_ref, *refs, **kw)
        q, k, v, do, lse, dlt, dk, dv, dk_scr, dv_scr = refs
        return _dkv_kernel(seed_ref, q, k, v, None, do, lse, dlt, dk, dv,
                           None, dk_scr, dv_scr, None, **kw)

    return wrapped


def _bwd_impl(q, k, v, bias, o, lse, do, scale, causal, block_q, block_k,
              seed=None, rate=0.0):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    nq, nk = Sq // block_q, Sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    # per-row values (lse/delta) carried as [B, H, S, 128] lane-broadcasts
    lse_t = jnp.broadcast_to(lse[..., None], (B, H, Sq, 128))
    dlt_t = jnp.broadcast_to(delta[..., None], (B, H, Sq, 128))

    qs = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    ks_j = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0))
    rowq = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, i, j: (b, h, i, 0))

    seed_specs = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  if rate > 0.0 else [])
    seed_args = [seed] if rate > 0.0 else []
    dq_in_specs = seed_specs + [qs, ks_j, ks_j]
    dq_args = seed_args + [q, k, v]
    if bias is not None:
        dq_in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                        lambda b, h, i, j: (b, 0, 0, j)))
        dq_args.append(bias)
    dq_in_specs += [qs, rowq, rowq]
    dq_args += [do, lse_t, dlt_t]

    dq = pl.pallas_call(
        _mk_kernel(_dq_kernel, bias is not None, has_seed=rate > 0.0,
                   scale=scale, causal=causal, block_q=block_q,
                   block_k=block_k, cd=_mxu_dtype(q.dtype), off=Sk - Sq,
                   rate=rate),
        grid=(B, H, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*dq_args)

    # dkv: grid (B, H, nk, nq) — i indexes k blocks, j indexes q blocks
    qs_j = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, j, 0))
    ks_i = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0))
    rowq_j = pl.BlockSpec((1, 1, block_q, 128),
                          lambda b, h, i, j: (b, h, j, 0))
    dkv_in_specs = seed_specs + [qs_j, ks_i, ks_i]
    dkv_args = seed_args + [q, k, v]
    if bias is not None:
        dkv_in_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                         lambda b, h, i, j: (b, 0, 0, i)))
        dkv_args.append(bias)
    dkv_in_specs += [qs_j, rowq_j, rowq_j]
    dkv_args += [do, lse_t, dlt_t]

    dkv_out_specs = [
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0)),
    ]
    dkv_out_shape = [
        jax.ShapeDtypeStruct(k.shape, k.dtype),
        jax.ShapeDtypeStruct(v.shape, v.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, D), jnp.float32),
        pltpu.VMEM((block_k, D), jnp.float32),
    ]
    if bias is not None:
        # per-(b, h) bias gradient rows; summed over heads below
        dkv_out_specs.append(pl.BlockSpec((1, 1, 1, block_k),
                                          lambda b, h, i, j: (b, h, 0, i)))
        dkv_out_shape.append(
            jax.ShapeDtypeStruct((B, H, 1, Sk), jnp.float32))
        dkv_scratch.append(pltpu.VMEM((8, block_k), jnp.float32))

    outs = pl.pallas_call(
        _mk_dkv_kernel(bias is not None, has_seed=rate > 0.0, scale=scale,
                       causal=causal, block_q=block_q, block_k=block_k,
                       cd=_mxu_dtype(q.dtype), off=Sk - Sq, rate=rate),
        grid=(B, H, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shape,
        scratch_shapes=dkv_scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(*dkv_args)
    if bias is not None:
        dk, dv, db_h = outs
        db = jnp.sum(db_h, axis=1, keepdims=True)        # [B, 1, 1, Sk]
        return dq, dk, dv, db
    dk, dv = outs
    return dq, dk, dv, None


# ---------------------------------------------------------------------------
# public entry (custom VJP over [B, H, S, D])
# ---------------------------------------------------------------------------


def _seed_arr(seed_f):
    """f32-bitcast seed words back to int32 (seed travels as a float arg so
    the custom_vjp can hand back a plain zero cotangent)."""
    return jax.lax.bitcast_convert_type(seed_f, jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, bias, seed_f, scale, causal, block_q, block_k, rate):
    o, _ = _fwd(q, k, v, bias, scale, causal, block_q, block_k,
                save_residuals=False, seed=_seed_arr(seed_f), rate=rate)
    return o


def _flash_fwd(q, k, v, bias, seed_f, scale, causal, block_q, block_k,
               rate):
    o, lse = _fwd(q, k, v, bias, scale, causal, block_q, block_k,
                  seed=_seed_arr(seed_f), rate=rate)
    return o, (q, k, v, bias, seed_f, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, rate, res, do):
    q, k, v, bias, seed_f, o, lse = res
    dq, dk, dv, db = _bwd_impl(q, k, v, bias, o, lse, do, scale, causal,
                               block_q, block_k, seed=_seed_arr(seed_f),
                               rate=rate)
    if bias is not None:
        db = db.astype(bias.dtype)
    return dq, dk, dv, db, jnp.zeros_like(seed_f)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pick_block(seq_len: int, requested: int) -> int:
    """Largest multiple of 128 that divides seq_len, capped at `requested`
    (so 768 -> 384 with the 512 default rather than failing)."""
    if seq_len % 128:
        raise ValueError(f"flash attention needs seq_len % 128 == 0, "
                         f"got {seq_len}")
    start = (min(requested, seq_len) // 128) * 128
    for b in range(start, 127, -128):
        if seq_len % b == 0:
            return b
    return 128


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK,
                    dropout_rate: float = 0.0, dropout_key=None):
    """Flash attention over [B, S, H, D] inputs (framework layout).

    bias: optional additive mask broadcastable to [B, 1, 1, Sk]
    (e.g. key padding: 0 keep, -1e30 masked).
    dropout_rate/dropout_key: in-kernel attention dropout via a stateless
    counter-based hash (works on TPU and in the interpreter); masks are
    regenerated from the seed in the backward, nothing is stored.
    Returns [B, S, H, D].
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = _pick_block(Sq, block_q)
    block_k = _pick_block(Sk, block_k)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if bias is not None:
        bias = jnp.broadcast_to(jnp.asarray(bias, jnp.float32),
                                (B, 1, 1, Sk))
    rate = float(dropout_rate)
    if rate >= 1.0:
        # everything dropped: defined all-zeros output (matches the XLA
        # composition); avoids 0/0 from the 1/(1-rate) scaling
        return jnp.zeros_like(q)
    if rate > 0.0:
        if dropout_key is None:
            raise ValueError("dropout_rate > 0 needs dropout_key")
        words = jax.random.key_data(dropout_key).ravel()[:2]
        seed_f = jax.lax.bitcast_convert_type(
            words.astype(jnp.uint32), jnp.float32)
    else:
        seed_f = jnp.zeros((2,), jnp.float32)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash(qt, kt, vt, bias, seed_f, float(scale), bool(causal),
               int(block_q), int(block_k), rate)
    return jnp.swapaxes(o, 1, 2)
