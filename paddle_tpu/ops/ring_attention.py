"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence parallelism (SURVEY §2.3: "Absent... must be
designed new") — long sequences there lean on recompute + fused kernels.
This module is the TPU-first design SURVEY §7.8 prescribes:

- **Ring attention**: Q stays put; K/V blocks rotate around the 'sp' mesh
  axis via `ppermute` (ICI neighbor exchanges). Each step computes local
  block attention and merges into a running (out, lse) with the numerically
  stable log-sum-exp combine — the cross-device generalization of the flash
  kernel's online softmax. Peak memory is O(S_local), enabling sequences
  n_sp times longer than one chip could hold.
- **Ulysses**: all-to-all swaps the sharded axis (sequence <-> heads), runs
  FULL-sequence attention on 1/n of the heads locally (dispatching to the
  Pallas flash kernel on TPU), and swaps back. Cheaper collectives for
  moderate S; requires num_heads % n == 0.

Both are plain functions over arrays, designed to run inside `shard_map`
over the mesh's 'sp' axis; `jax.grad` differentiates through them
(ppermute/all_to_all have registered transposes), so no custom VJP needed.

Block attention is computed in f32 with the framework matmul policy; causal
ring steps pick full/causal/skip per K/V-block origin with `lax.switch`.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.flags import matmul_precision

__all__ = ["ring_attention", "ulysses_attention", "block_attention"]

NEG_INF = -1e30


def _pcast_varying(x, axis_name):
    """Mark ``x`` as device-varying over ``axis_name`` for shard_map's VMA
    type checking (jax >= 0.5). Legacy jax has neither lax.pcast nor VMA
    typing, where this is correctly a no-op."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return x


def block_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None):
    """Local attention returning (out, lse) for cross-block merging.

    q: [B, Sq, H, D], k/v: [B, Sk, H, D] -> out [B, Sq, H, D],
    lse [B, Sq, H] (f32). The XLA composition; block sizes inside the ring
    are S_local so XLA's fusion handles them well.
    """
    B, Sq, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    prec = matmul_precision()
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=prec) * scale
    s = s.astype(jnp.float32)
    if causal:
        Sk = k.shape[1]
        cmask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        s = jnp.where(cmask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.where(m <= NEG_INF, 0.0, m)
    p = jnp.exp(s - m_safe)
    p = jnp.where(s <= NEG_INF, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhqk,bkhd->bqhd", (p / l_safe).astype(q.dtype), v,
                   precision=prec)
    lse = jnp.where(l[..., 0] == 0.0, NEG_INF, m[..., 0] + jnp.log(l_safe[..., 0]))
    return o, jnp.swapaxes(lse, 1, 2)      # lse -> [B, Sq, H]


def _merge(o_a, lse_a, o_b, lse_b):
    """Combine two attention partials over disjoint key sets.

    The accumulator (o_a) stays f32 across ring steps — casting back to
    bf16 every step would compound ~n rounding truncations."""
    m = jnp.maximum(lse_a, lse_b)
    m_safe = jnp.where(m <= NEG_INF, 0.0, m)
    wa = jnp.exp(lse_a - m_safe)
    wb = jnp.exp(lse_b - m_safe)
    denom = wa + wb
    denom = jnp.where(denom == 0.0, 1.0, denom)
    o = (o_a.astype(jnp.float32) * (wa / denom)[..., None]
         + o_b.astype(jnp.float32) * (wb / denom)[..., None])
    lse = m + jnp.log(denom)
    lse = jnp.where(m <= NEG_INF, NEG_INF, lse)
    return o, lse


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over a sharded sequence (call inside shard_map).

    q/k/v: LOCAL shards [B, S_local, H, D]; the sequence axis is sharded
    over ``axis_name``. K/V rotate n times by `ppermute`; causal masking is
    exact: earlier-rank blocks attend fully, the home block causally, later
    blocks are skipped (they contribute -inf lse).
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]   # kv moves to next rank

    def step(carry, _):
        o_acc, lse_acc, kb, vb, src = carry
        # kb/vb originated at rank `src`
        def full(_):
            return block_attention(q, kb, vb, causal=False, scale=scale)

        def diag(_):
            return block_attention(q, kb, vb, causal=True, scale=scale)

        def skip(_):
            z = jnp.full(q.shape[:2] + (q.shape[2],), NEG_INF, jnp.float32)
            return (jnp.zeros_like(q),
                    _pcast_varying(z, axis_name))

        if causal:
            rel = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            o_i, lse_i = lax.switch(rel, [full, diag, skip], None)
        else:
            o_i, lse_i = full(None)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_i, lse_i)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        src = (src - 1) % n                     # our kv now came from src-1
        return (o_acc, lse_acc, kb, vb, src), None

    o0 = jnp.zeros(q.shape, jnp.float32)   # f32 accumulator (see _merge)
    lse0 = jnp.full(q.shape[:2] + (q.shape[2],), NEG_INF, jnp.float32)
    # mark the constant initial carries as device-varying so the scan carry
    # type matches the per-device outputs under shard_map's vma checking
    o0 = _pcast_varying(o0, axis_name)
    lse0 = _pcast_varying(lse0, axis_name)
    (o, lse, _, _, _), _ = lax.scan(step, (o0, lse0, k, v, my), None,
                                    length=n)
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                      scale: Optional[float] = None, use_flash: bool = True):
    """Ulysses SP (call inside shard_map): all-to-all seq<->heads, full
    attention on the local head slice, all-to-all back.

    q/k/v: LOCAL shards [B, S_local, H, D] with H % n == 0. After the first
    all_to_all each device holds [B, S_full, H/n, D].
    """
    n = lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [B, S/n, H, D] -> gather seq, scatter heads -> [B, S, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    from .attention import sdpa_array
    of = sdpa_array(qf, kf, vf, mask=None, dropout_p=0.0, is_causal=causal,
                    use_flash=use_flash)
    return heads_to_seq(of.astype(q.dtype))
