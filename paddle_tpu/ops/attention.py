"""Attention ops: XLA composition + Pallas flash-attention dispatch.

Replaces the reference's fused attention stack
(reference: paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h)
with a TPU design: a flash-attention Pallas kernel for the hot path and an
XLA softmax composition fallback (XLA already fuses scale+mask+softmax into
the surrounding matmuls well).
Layout convention: [batch, seq, heads, head_dim] (paddle MultiHeadAttention
uses [B, S, H*D] outside, [B, H, S, D] inside scores).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.flags import matmul_precision
from ..core.random import make_rng
from ..core.tensor import Tensor, apply


def _sdpa_xla(q, k, v, mask, dropout_p, is_causal, dropout_key):
    """Reference composition: works on [B, S, H, D]."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    prec = matmul_precision()
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=prec) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        scores = jnp.where(causal[None, None], scores, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -1e30)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v, precision=prec)


def _flash_supported(q, k, v, mask, dropout_p, dropout_key=None) -> bool:
    if dropout_p > 0.0 and dropout_key is None:
        # no key: the XLA path silently skips dropout — keep that behavior
        # shape-independent rather than raising only on flash-eligible
        # shapes
        return False
    if mask is not None:
        # only additive key-padding masks [B, 1, 1, Sk] fit the kernel
        if (mask.dtype == jnp.bool_ or mask.ndim != 4
                or mask.shape[1] != 1 or mask.shape[2] != 1):
            return False
    B, S, H, D = q.shape
    Sk = k.shape[1]
    return (
        jax.default_backend() == "tpu"
        and S % 128 == 0 and Sk % 128 == 0
        and D in (64, 128, 256)
        and S >= 256
    )


def sdpa_array(q, k, v, mask=None, dropout_p=0.0, is_causal=False,
               dropout_key=None, use_flash=True):
    """Raw-array scaled dot-product attention with flash dispatch."""
    if use_flash and _flash_supported(q, k, v, mask, dropout_p,
                                      dropout_key):
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, bias=mask, causal=is_causal,
                               dropout_rate=dropout_p,
                               dropout_key=dropout_key)
    return _sdpa_xla(q, k, v, mask, dropout_p, is_causal, dropout_key)


def scaled_dot_product_attention(query: Tensor, key: Tensor, value: Tensor,
                                 attn_mask: Optional[Tensor] = None,
                                 dropout_p: float = 0.0, is_causal: bool = False,
                                 training: bool = True) -> Tensor:
    dk = make_rng() if (dropout_p > 0.0 and training) else None
    p = dropout_p if training else 0.0

    def _fn(q, k, v, *maybe_mask):
        m = maybe_mask[0] if maybe_mask else None
        return sdpa_array(q, k, v, m, p, is_causal, dk)

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply(_fn, *args, name="scaled_dot_product_attention")
