"""Hand-optimised TPU ops: Pallas kernels + fused XLA compositions.

This package replaces the reference's `operators/fused/` CUDA kernels
(fused_attention_op.cu, fused_feedforward_op.cu, fused_dropout_helper.h):
on TPU, XLA fuses most epilogues automatically, so only genuinely
fusion-resistant patterns (flash attention tiling, ring attention
communication overlap) get Pallas kernels.
"""

from . import attention  # noqa: F401
