"""Signal processing: frame / overlap_add / stft / istft.

reference parity: python/paddle/signal.py (frame:32, overlap_add:153,
stft:236, istft:390 — including center padding, window application,
onesided spectra and NOLA normalization on reconstruction).

TPU-native: frames are gathered with a static [num_frames, frame_length]
index matrix (one jnp.take — XLA turns it into an efficient gather);
overlap-add is a segment_sum over the same index map. Everything is
jit-compilable with static shapes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .core.tensor import Tensor, apply

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slice into overlapping frames along ``axis`` (reference:
    signal.py:32). axis=-1: [..., seq] -> [..., frame_length, num_frames];
    axis=0: [seq, ...] -> [num_frames, frame_length, ...]."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    x = _as_tensor(x)
    seq = x.shape[axis]
    if frame_length > seq:
        raise ValueError(f"frame_length {frame_length} > seq {seq}")
    n_frames = 1 + (seq - frame_length) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]

    def impl(a):
        taken = jnp.take(a, idx, axis=axis if axis >= 0 else a.ndim - 1)
        if axis in (-1, a.ndim - 1):
            # [..., n_frames, frame_length] -> [..., frame_length, n_frames]
            return jnp.swapaxes(taken, -1, -2)
        return taken                      # axis == 0: [n_frames, fl, ...]
    return apply(impl, x, name="frame")


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of frame (reference: signal.py:153). axis=-1:
    [..., frame_length, n_frames] -> [..., seq]."""
    x = _as_tensor(x)

    def impl(a):
        if axis in (-1, a.ndim - 1):
            fl, nf = a.shape[-2], a.shape[-1]
            frames = jnp.swapaxes(a, -1, -2)       # [..., nf, fl]
        else:
            nf, fl = a.shape[0], a.shape[1]
            frames = jnp.moveaxis(a, (0, 1), (-2, -1))  # [..., nf, fl]
        seq = (nf - 1) * hop_length + fl
        starts = jnp.arange(nf) * hop_length
        idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        flat = frames.reshape(frames.shape[:-2] + (nf * fl,))
        out = jax.vmap(
            lambda row: jnp.zeros((seq,), a.dtype).at[idx].add(row)
        )(flat.reshape((-1, nf * fl)))
        out = out.reshape(frames.shape[:-2] + (seq,))
        if axis in (-1, a.ndim - 1):
            return out
        return jnp.moveaxis(out, -1, 0)
    return apply(impl, x, name="overlap_add")


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """STFT (reference: signal.py:236). x: [..., seq_len]. Returns
    [..., n_fft//2+1 or n_fft, num_frames] complex."""
    x = _as_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        w = window._data if isinstance(window, Tensor) else jnp.asarray(window)
        if w.shape[-1] != win_length:
            raise ValueError("window length mismatch")
    else:
        w = jnp.ones((win_length,), jnp.float32)
    # center the window inside n_fft
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))

    def impl(a, wa):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        seq = a.shape[-1]
        nf = 1 + (seq - n_fft) // hop_length
        starts = jnp.arange(nf) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = jnp.take(a, idx, axis=a.ndim - 1)     # [..., nf, n_fft]
        frames = frames * wa
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))     # [..., nf, bins]
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)              # [..., bins, nf]

    return apply(impl, x, Tensor(w), name="stft")


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """ISTFT with NOLA normalization (reference: signal.py:390).
    x: [..., bins, num_frames] complex."""
    x = _as_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if return_complex and onesided:
        raise ValueError(
            "istft: return_complex=True requires onesided=False (a "
            "onesided spectrum can only reconstruct a real signal)")
    if window is not None:
        w = window._data if isinstance(window, Tensor) else jnp.asarray(window)
    else:
        w = jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))

    def impl(a, wa):
        spec = jnp.swapaxes(a, -1, -2)                  # [..., nf, bins]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)        # complex
            if not return_complex:
                frames = frames.real
        frames = frames * wa
        nf = frames.shape[-2]
        seq = (nf - 1) * hop_length + n_fft
        starts = jnp.arange(nf) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        flat = frames.reshape((-1, nf * n_fft))
        sig = jax.vmap(
            lambda row: jnp.zeros((seq,), frames.dtype).at[idx].add(row)
        )(flat)
        sig = sig.reshape(frames.shape[:-2] + (seq,))
        # NOLA: divide by the summed squared window envelope
        wsq = jnp.tile(wa * wa, (nf, 1)).reshape(-1)
        envelope = jnp.zeros((seq,), wa.dtype).at[idx].add(wsq)
        sig = sig / jnp.where(envelope > 1e-11, envelope, 1.0)
        if center:
            sig = sig[..., n_fft // 2:seq - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig

    return apply(impl, x, Tensor(w), name="istft")
