"""to_static: compile Layers/functions to cached XLA executables.

Design (vs reference program_translator.py:768):
- Forward inference: one jitted pure function per input signature.
- Eager-tape training through a StaticFunction: the whole compiled call
  becomes ONE tape node; its backward re-runs the compiled VJP (forward
  rematerialised inside the compiled backward — everything stays in XLA).
- The real training hot path is :class:`TrainStep`, which compiles
  forward+loss+grad+optimizer into a single donated-buffer executable
  (the analogue of the reference's whole-Program execution).
"""

from __future__ import annotations

import contextlib
import functools
import time
import weakref
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.random import make_rng, trace_rng
from ..core.tensor import TapeNode, Tensor, is_grad_enabled, no_grad
from ..nn.layer import Layer
from ..testing import chaos as _chaos
from .functional import (bind, buffer_arrays, param_arrays,
                         trainable_param_arrays, unwrap, wrap)
from .input_spec import InputSpec


def _sig_of(arrays):
    leaves, treedef = jax.tree_util.tree_flatten(arrays)
    return (tuple((a.shape, str(a.dtype)) if hasattr(a, "shape") else (type(a), a)
                  for a in leaves), treedef)


_CONTROL_FLOW_GUIDANCE = (
    "\n\nThis happened while compiling (tracing) the model: python "
    "control flow branched on a TRACED tensor value, which has no "
    "concrete value at compile time (reference analogue: the AST "
    "translator of program_translator.py rewrites `if`/`while` on "
    "tensors into conditional_block/while ops). The TPU-native fixes:\n"
    "  - paddle.static.nn.cond(pred, true_fn, false_fn) for tensor-"
    "dependent branches (compiles both, selects on device);\n"
    "  - paddle.static.nn.while_loop(cond_fn, body_fn, vars) for "
    "tensor-dependent loops;\n"
    "  - jnp.where / paddle.where for elementwise selects;\n"
    "  - move the branch decision to host data (python scalars) if it "
    "is static per call."
)


@contextlib.contextmanager
def _control_flow_guidance():
    """Append framework guidance to tracer-concretization errors (the
    exception object is re-raised with an amended message so user
    except-clauses keep matching the jax type)."""
    import jax.errors
    try:
        yield
    except jax.errors.ConcretizationTypeError as e:
        e.args = (str(e) + _CONTROL_FLOW_GUIDANCE,)
        raise


class StaticFunction:
    """Callable wrapping a Layer's forward (or a plain fn) with jit caching."""

    def __init__(self, function: Callable, layer: Optional[Layer] = None,
                 input_spec=None):
        self._fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._cache: Dict[Any, Callable] = {}
        self._bwd_cache: Dict[Any, Callable] = {}
        functools.update_wrapper(self, function)

    # -- pure function factory ---------------------------------------------
    def _pure(self, treedef, kwargs):
        layer = self._layer
        fn = self._fn
        training = layer.training if layer is not None else False

        def pure(p_arrays, b_arrays, key, flat_inputs):
            inputs = jax.tree_util.tree_unflatten(treedef, flat_inputs)
            tensors = [Tensor(a) if isinstance(a, (jax.Array, jnp.ndarray)) or
                       hasattr(a, "dtype") else a for a in inputs]
            bufs = dict(b_arrays)
            with trace_rng(key), no_grad():
                if layer is not None:
                    with bind(layer, p_arrays, bufs):
                        out = fn(*tensors, **kwargs)
                else:
                    out = fn(*tensors, **kwargs)
            return unwrap(out), bufs

        return pure

    def __call__(self, *args, **kwargs):
        layer = self._layer
        p_arrays = param_arrays(layer) if layer is not None else {}
        b_arrays = buffer_arrays(layer) if layer is not None else {}
        raw_inputs = [a._data if isinstance(a, Tensor) else a for a in args]
        flat_inputs, treedef = jax.tree_util.tree_flatten(raw_inputs)
        key = make_rng("to_static")

        sig = (_sig_of(flat_inputs)[0], treedef,
               tuple(sorted(kwargs.items())) if kwargs else (),
               layer.training if layer is not None else False)

        jitted = self._cache.get(sig)
        if jitted is None:
            pure = self._pure(treedef, kwargs)
            jitted = jax.jit(pure)
            self._cache[sig] = jitted

        needs_grad = False
        if is_grad_enabled() and layer is not None:
            needs_grad = any(not p.stop_gradient
                             for p in layer.parameters())

        if not needs_grad:
            with _control_flow_guidance():
                out_arrays, new_bufs = jitted(p_arrays, b_arrays, key,
                                              flat_inputs)
            if layer is not None:
                for k, b in layer.named_buffers():
                    if k in new_bufs:
                        b._data = new_bufs[k]
            return wrap(out_arrays)

        # training path: one fused tape node, compiled remat backward
        t_params = {k: p for k, p in layer.named_parameters()
                    if not p.stop_gradient}
        t_arrays = {k: p._data for k, p in t_params.items()}
        frozen = {k: v for k, v in p_arrays.items() if k not in t_arrays}

        pure = self._pure(treedef, kwargs)

        with _control_flow_guidance():
            out_arrays, new_bufs = jitted(p_arrays, b_arrays, key,
                                          flat_inputs)

        bwd = self._bwd_cache.get(sig)
        if bwd is None:
            # key/buffers/frozen are explicit arguments (NOT closed over):
            # the cached executable must rematerialize the forward with the
            # *current* call's RNG key and buffers, or dropout masks in the
            # recomputed forward would come from the first call.
            def bwd_fn(t_a, frozen_a, b_a, k, flat_in, cotangents):
                def f(t_a_inner, flat_inner):
                    out, _ = pure({**frozen_a, **t_a_inner}, b_a, k, flat_inner)
                    return out
                _, vjp = jax.vjp(f, t_a, flat_in)
                return vjp(cotangents)
            bwd = jax.jit(bwd_fn)
            self._bwd_cache[sig] = bwd

        # tape node over (param tensors + diff input tensors)
        diff_inputs = [a for a in args if isinstance(a, Tensor)
                       and not a.stop_gradient]
        node_inputs = list(t_params.values()) + diff_inputs

        out_leaves, out_treedef = jax.tree_util.tree_flatten(out_arrays)
        out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_leaves]

        captured_inputs = list(flat_inputs)
        captured_key = key
        captured_bufs = b_arrays

        def vjp_fn(cots):
            cot_list = list(cots) if isinstance(cots, tuple) else [cots]
            cot_tree = jax.tree_util.tree_unflatten(out_treedef, cot_list)
            g_params, g_inputs = bwd(t_arrays, frozen, captured_bufs,
                                     captured_key, captured_inputs, cot_tree)
            grads = [g_params[k] for k in t_params.keys()]
            # map input grads back to diff tensor positions
            flat_gin, _ = jax.tree_util.tree_flatten(g_inputs)
            idx = 0
            for a in args:
                if isinstance(a, Tensor) and not a.stop_gradient:
                    grads.append(flat_gin[idx])
                if isinstance(a, Tensor):
                    idx += 1
            return tuple(grads)

        node = TapeNode(vjp_fn, node_inputs, out_avals, name="to_static")
        out_tensors = []
        for i, arr in enumerate(out_leaves):
            t = Tensor(arr, stop_gradient=not jnp.issubdtype(arr.dtype, jnp.floating))
            if not t.stop_gradient:
                t._node = node
                t._out_idx = i
                node.out_refs[i] = weakref.ref(t)
            out_tensors.append(t)
        if layer is not None:
            for k, b in layer.named_buffers():
                if k in new_bufs:
                    b._data = new_bufs[k]
        return jax.tree_util.tree_unflatten(out_treedef, out_tensors)

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)

    def concrete_program(self, *args):
        return None  # parity shim


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper compiling a Layer or function."""

    def _decorate(obj):
        from .dy2static import convert_to_static
        if isinstance(obj, Layer):
            fwd = convert_to_static(type(obj).forward).__get__(obj)
            static = StaticFunction(fwd, layer=obj, input_spec=input_spec)
            obj.forward = static
            return obj
        # plain function or unbound Layer.forward; python if/while over
        # tensors is functionalized by the dy2static AST pass (reference:
        # program_translator.py ProgramTranslator)
        fn = getattr(obj, "__func__", obj)
        bound = getattr(obj, "__self__", None)
        converted = convert_to_static(fn)
        if bound is not None:
            converted = converted.__get__(bound)
        return StaticFunction(converted, layer=bound,
                              input_spec=input_spec)

    if function is not None:
        return _decorate(function)
    return _decorate


def _layer_key(name: str) -> str:
    """Group a state-dict parameter name into its layer bucket: the
    prefix up to and including the first numeric path component
    (``layers.0.attn.qkv_weight`` → ``layers.0``), else the first
    component (``embed.weight`` → ``embed``). Scan-over-layers keeps
    per-layer state-dict names (nn/scan.py stacks at trace time only),
    so the grouping is layout-invariant."""
    parts = name.split(".")
    for i, p in enumerate(parts[:-1]):
        if p.isdigit():
            return ".".join(parts[:i + 1])
    return parts[0]


def _layer_health_outputs(old_params, new_params, grads):
    """Per-layer f32 health vectors computed INSIDE the compiled step
    (FLAGS_train_health_every): grad norm, post-update param norm, and
    the update ratio ||new-old|| / (||old|| + eps) — the classic
    training-health triple. A handful of reductions fused into the step
    program; no extra dispatch."""
    groups: Dict[str, list] = {}
    for k in grads:
        groups.setdefault(_layer_key(k), []).append(k)

    def sumsq(tree, ks):
        tot = jnp.zeros((), jnp.float32)
        for k in ks:
            a = tree[k]
            tot = tot + jnp.sum(jnp.square(a.astype(jnp.float32)))
        return tot

    out = {}
    for layer, ks in sorted(groups.items()):
        old_norm = jnp.sqrt(sumsq(old_params, ks))
        upd = jnp.sqrt(sum(
            jnp.sum(jnp.square((new_params[k] - old_params[k]
                                ).astype(jnp.float32))) for k in ks))
        out[layer] = {
            "grad_norm": jnp.sqrt(sumsq(grads, ks)),
            "param_norm": jnp.sqrt(sumsq(new_params, ks)),
            "update_ratio": upd / (old_norm + 1e-12),
        }
    return out


def _donation_safe() -> bool:
    """jax 0.4.37 XLA:CPU hazard: executables reloaded from the PERSISTENT
    compilation cache can lose the input-output aliasing of donated
    buffers when the program contains while/scan bodies (the
    scan-over-layers train step) — warm-cache steps then read clobbered
    parameter buffers and return garbage losses (segfaults observed too).
    Reproduced with a pure-jax scan+grad+donate step on this CPU backend;
    TPU executable serialization is unaffected. Donation is therefore
    kept everywhere EXCEPT cpu-backend-with-persistent-cache (the test
    environment, where donation buys nothing)."""
    if jax.default_backend() != "cpu":
        return True
    return not (jax.config.jax_compilation_cache_dir or "")


class TrainStep:
    """Compile (model, loss, optimizer) into ONE donated XLA train step.

    The TPU-native answer to the reference's static-graph training loop
    (Program + Executor): params/opt-state live as device arrays owned by
    this object; each step is a single compiled call with buffer donation.

    SPMD: pass ``mesh`` (or have fleet.init set one) and a ``data_spec``
    PartitionSpec for the batch; parameters are laid out per their
    ``Parameter.spec`` annotations, optimizer slots inherit the param
    sharding, and ``zero_axis`` additionally shards replicated slots over
    that mesh axis — ZeRO-1 optimizer-state partitioning (reference:
    fleet/meta_optimizers/sharding_optimizer.py:72; here a layout
    declaration, the weight-update all-gather is inserted by XLA).

    `sync_to_layer()` writes values back into the Layer for checkpointing /
    eager inspection.
    """

    def __new__(cls, layer=None, loss_fn=None, optimizer=None, *args,
                **kwargs):
        # fleet meta-optimizer dispatch (reference: strategy_compiler.py
        # picks the meta-optimizer from the strategy attached at
        # fleet.distributed_optimizer): a strategy snapshot carried by the
        # optimizer selects the LocalSGD step implementation. Strategy is
        # read ONLY from the optimizer — never from process globals — so
        # a bare optimizer always gets the plain step.
        strat = getattr(optimizer, "_fleet_strategy", None)
        if cls is TrainStep and strat is not None and (
                strat.localsgd or strat.adaptive_localsgd):
            from ..distributed.fleet.meta_optimizers import LocalSGDTrainStep
            from ..distributed.fleet.topology import (
                get_hybrid_communicate_group)
            hcg = get_hybrid_communicate_group()
            if hcg is None:
                raise RuntimeError(
                    "strategy.localsgd requires fleet.init() first (the dp "
                    "mesh axis hosts the per-replica parameter copies)")
            if strat.gradient_merge:
                raise NotImplementedError(
                    "strategy combines localsgd with gradient_merge; the "
                    "LocalSGD step does not accumulate gradients — pick "
                    "one (the reference's meta-optimizer chain rejects "
                    "this pairing too)")
            # arguments the LocalSGD step cannot honor must fail loudly,
            # not vanish (the silent-rewiring failure mode this dispatch
            # exists to eliminate)
            unsupported = {k: v for k, v in kwargs.items()
                           if k not in ("mesh", "data_spec") and
                           v is not None and v is not True}
            if args or unsupported:
                raise TypeError(
                    "strategy.localsgd builds a LocalSGDTrainStep, which "
                    f"does not accept {list(unsupported) or 'positional'} "
                    "arguments (metrics_fn/zero_axis/grad_accum_*); "
                    "construct distributed.fleet.meta_optimizers."
                    "LocalSGDTrainStep directly for custom wiring")
            adaptive = bool(strat.adaptive_localsgd)
            cfg = (strat.adaptive_localsgd_configs if adaptive
                   else strat.localsgd_configs)
            k = int(cfg.get("init_k_steps" if adaptive else "k_steps", 1))
            return LocalSGDTrainStep(
                layer, loss_fn, optimizer,
                kwargs.get("mesh") or hcg.mesh, k_steps=k,
                axis="dp", adaptive=adaptive)
        return super().__new__(cls)

    def __init__(self, layer: Layer, loss_fn: Callable, optimizer,
                 metrics_fn: Optional[Callable] = None, donate: bool = True,
                 mesh=None, data_spec=None, zero_axis: Optional[str] = None,
                 grad_accum_steps: Optional[int] = None,
                 grad_accum_avg: Optional[bool] = None,
                 check_numerics=False,
                 skip_nonfinite_budget: int = 0):
        from ..distributed import env as dist_env
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.metrics_fn = metrics_fn
        if grad_accum_steps is None:
            # gradient merge comes ONLY from the strategy snapshot that
            # fleet.distributed_optimizer attached to this optimizer
            # (reference: gradient_merge_optimizer.py, applied by the
            # meta-optimizer chain at the distributed_optimizer boundary).
            # A bare optimizer is never silently rewired by fleet.init.
            grad_accum_steps = 1
            strat = getattr(optimizer, "_fleet_strategy", None)
            if strat is not None and strat.gradient_merge:
                cfg = strat.gradient_merge_configs
                grad_accum_steps = int(cfg["k_steps"])
                if grad_accum_avg is None:
                    grad_accum_avg = bool(cfg.get("avg", True))
        self.grad_accum_steps = max(1, int(grad_accum_steps))
        self.grad_accum_avg = True if grad_accum_avg is None \
            else grad_accum_avg
        self._acc_grads = None
        self._micro_count = 0
        self.mesh = mesh if mesh is not None else (
            dist_env.get_mesh() if data_spec is not None or zero_axis else None)
        self.data_spec = data_spec
        self.zero_axis = zero_axis
        if self.mesh is not None:
            if dist_env.get_mesh() is None:
                dist_env.set_mesh(self.mesh)
            from ..distributed.spmd import apply_param_shardings
            apply_param_shardings(layer, self.mesh)
        self.params = trainable_param_arrays(layer)
        self.frozen = {k: v for k, v in param_arrays(layer).items()
                       if k not in self.params}
        self.buffers = buffer_arrays(layer)
        self.opt_state = optimizer.init_state(self.params)
        if self.mesh is not None:
            self._layout_opt_state()
        self.step_count = 0
        self._jitted: Dict[Any, Callable] = {}
        self._donate = donate
        # -- telemetry (paddle_tpu.monitor; docs/OBSERVABILITY.md) ---------
        # check_numerics: opt-in eager NaN/Inf watchdog — the post-step
        # loss check runs OUTSIDE the compiled program (XLA fusion
        # untouched; contrast FLAGS_check_nan_inf, which compiles finite
        # flags into the step). The post-mortem grads pass needs the
        # PRE-update params/buffers alive after the step, so donation is
        # off in this mode. Values: False | True/"raise" | "warn".
        self._check_numerics = check_numerics
        # skip_nonfinite_budget: graceful degradation on a transient
        # numeric fault (GradScaler-style, docs/FAULT_TOLERANCE.md). On
        # a non-finite loss the whole update (params/opt-state/step
        # count) is ROLLED BACK and training continues; only after N
        # CONSECUTIVE skips does the trip raise — a single bad batch on
        # a week-long run is an event, not a crash. Needs the watchdog's
        # pre-update state alive, so donation is off in this mode too.
        self.skip_nonfinite_budget = max(0, int(skip_nonfinite_budget))
        self._consecutive_skips = 0
        if check_numerics or self.skip_nonfinite_budget:
            self._donate = False
        self._kinds_compiled: set = set()
        self._stats = {"compiles": 0, "recompiles": 0,
                       "grad_accum_syncs": 0, "nonfinite_trips": 0,
                       "nonfinite_skips": 0, "health_spikes": 0}
        # EWMA spike detector over the per-layer health side-outputs;
        # allocated on the first publish (FLAGS_train_health_every > 0)
        self._health_mon = None
        # per-program-kind attribution (ISSUE 4): cost from
        # lowered.cost_analysis(), HBM budget from
        # compiled.memory_analysis() — captured once per compile (never
        # on the step hot path), readable via stats() with monitor off
        self._programs: Dict[str, dict] = {}
        self._program_memory: Dict[str, Any] = {}
        self._wall_ema: Dict[str, float] = {}
        self._peak_flops_cache = None
        from ..core.flags import get_flag
        if get_flag("flight_recorder"):
            # crash forensics opt-in: excepthook + faulthandler dump
            # hooks from the first TrainStep on (docs/OBSERVABILITY.md)
            from ..monitor.flight_recorder import get_flight_recorder
            get_flight_recorder().install()
        if int(get_flag("monitor_port") or 0):
            # live telemetry plane opt-in for training runs: /metrics,
            # /statusz (this step registers its stats() as a section),
            # /debug/profile on the live process. Flag unset = one int
            # read, nothing else (docs/OBSERVABILITY.md).
            from ..monitor import server as monitor_server
            srv = monitor_server.maybe_start_from_flags()
            if srv is not None:
                import weakref
                ref = weakref.ref(self)
                stale = monitor_server.STALE
                srv.register_status(
                    f"train_step-{id(self)}",
                    lambda: (lambda s: s.stats() if s is not None
                             else stale)(ref()))
                from ..monitor import goodput as _goodput
                srv.register_status("goodput", _goodput.statusz_section)
        from ..core.tensor import eager_cache_stats
        from ..utils.compilation import compile_counts
        self._cc0 = compile_counts()
        self._ec0 = eager_cache_stats()
        # pipeline-aware dispatch guard: when the model carries an SPMD
        # pipeline over a pp>1 mesh, the whole step program IS the
        # pipeline dispatch path — run it under the PR 5 collective
        # watchdog (FLAGS_collective_timeout_s + chaos collective.hang)
        # so a hung stage handoff raises CollectiveTimeoutError on the
        # controller instead of stalling training (docs/PARALLELISM.md).
        self._pp_degree = 0
        try:
            from ..distributed.meta_parallel.spmd_pipeline import (
                PipelineStageStack)
            for sub in layer.sublayers(include_self=True):
                if isinstance(sub, PipelineStageStack):
                    self._pp_degree = max(self._pp_degree,
                                          sub._pp_degree())
        except Exception:
            pass
        # same guard for models carrying expert-parallel MoE layers over
        # an ep>1 mesh: the step program contains the expert all_to_alls
        # (ISSUE 10 — a hung expert exchange must raise structured)
        self._ep_degree = 0
        if not self._pp_degree:
            try:
                from ..incubate.moe import MoELayer
                for sub in layer.sublayers(include_self=True):
                    if isinstance(sub, MoELayer) and sub._stacked:
                        self._ep_degree = max(self._ep_degree,
                                              sub._ep_degree())
            except Exception:
                pass

    def _dispatch(self, jitted, *args):
        """Invoke a compiled step program; pipeline- and expert-parallel-
        carrying steps run under the collective watchdog (zero overhead
        with the timeout flag unset and no chaos armed)."""
        if self._pp_degree > 1:
            from ..distributed import collective as _coll
            from ..distributed.meta_parallel.spmd_pipeline import _pp_group
            return _coll._run_collective(
                "pipeline_step", _pp_group(self._pp_degree), jitted, *args)
        if self._ep_degree > 1:
            from ..distributed import collective as _coll
            from ..incubate.moe import moe_ep_group
            return _coll._run_collective(
                "moe_step", moe_ep_group(self._ep_degree), jitted, *args)
        return jitted(*args)

    # -- SPMD layout -------------------------------------------------------
    def _param_specs(self):
        from jax.sharding import PartitionSpec as P

        from ..distributed.spmd import degrade_spec
        specs = {}
        for k, p in self.layer.named_parameters():
            if k in self.params:
                spec = getattr(p, "spec", None) or P()
                # spec axes absent from THIS mesh degrade to replicated —
                # e.g. mp-annotated weights on an ep-only mesh
                if self.mesh is not None:
                    spec = degrade_spec(spec, self.mesh)
                specs[k] = spec
        return specs

    def _slot_spec(self, k, shape):
        """Optimizer-slot spec: param spec, plus ZeRO sharding of the first
        free, divisible dim over ``zero_axis``."""
        from jax.sharding import PartitionSpec as P
        spec = tuple(self._specs.get(k, P()))
        spec = spec + (None,) * (len(shape) - len(spec))
        if self.zero_axis and self.zero_axis in self.mesh.axis_names:
            z = self.mesh.shape[self.zero_axis]
            for i, (s, d) in enumerate(zip(spec, shape)):
                if s is None and d % z == 0 and d >= z:
                    spec = spec[:i] + (self.zero_axis,) + spec[i + 1:]
                    break
        return P(*spec)

    def _layout_opt_state(self):
        from jax.sharding import NamedSharding

        self._specs = self._param_specs()

        def place(k, slot):
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(
                    a, NamedSharding(self.mesh, self._slot_spec(k, a.shape)))
                if hasattr(a, "shape") and a.ndim > 0 else a, slot)

        self.opt_state = {k: place(k, v) for k, v in self.opt_state.items()}

    def _place_batch(self, raw):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self.mesh is None or self.data_spec is None:
            return raw
        spec = tuple(self.data_spec)

        def put(a):
            if not hasattr(a, "ndim"):
                return a
            s = spec[:a.ndim] + (None,) * max(0, a.ndim - len(spec))
            return jax.device_put(a, NamedSharding(self.mesh, P(*s)))

        return [put(a) for a in raw]

    def _loss_and_grads(self, treedef):
        """Shared fwd+bwd kernel: (params, buffers, key, flat_batch) ->
        ((loss, new_bufs), grads)."""
        from ..core.flags import get_flag
        from ..nn import layout as nn_layout
        layer, loss_fn, frozen = self.layer, self.loss_fn, self.frozen
        # automatic NHWC rewrite (FLAGS_jit_channels_last): the trace runs
        # under the channels-last planner, so any 2-D NCHW conv/BN/pool
        # chain in the model compiles MXU-native — one layout transpose at
        # model entry/exit instead of per-op NCHW dimension numbers. Pure
        # python tracing state: numerics are layout-invariant (covered by
        # the NCHW/NHWC parity tests) and the flag is read at trace time.
        channels_last = bool(get_flag("jit_channels_last"))

        def run(params, buffers, key, flat_batch):
            batch = jax.tree_util.tree_unflatten(treedef, flat_batch)

            def compute_loss(p):
                tensors = [Tensor(b) for b in batch]
                bufs = dict(buffers)
                with trace_rng(key), no_grad(), \
                        nn_layout.channels_last_scope(channels_last):
                    with bind(layer, {**frozen, **p}, bufs):
                        loss = loss_fn(layer, *tensors)
                loss_arr = loss._data if isinstance(loss, Tensor) else loss
                return loss_arr.astype(jnp.float32), bufs

            return jax.value_and_grad(compute_loss, has_aux=True)(params)

        return run

    def _make_step(self, treedef, training=True, check_finite=False,
                   health=False):
        optimizer = self.optimizer
        run = self._loss_and_grads(treedef)

        def step(params, buffers, opt_state, lr, t, key, flat_batch):
            (loss, new_bufs), grads = run(params, buffers, key, flat_batch)
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state, lr, t)
            out = (new_params, new_bufs, new_opt, loss)
            if check_finite:
                # NaN/Inf debug under jit (reference: FLAGS_check_nan_inf +
                # nan_inf_utils: per-op device-side scan; here per-gradient
                # + loss flags, cheap booleans fetched with the loss)
                flags = {"loss": jnp.isfinite(loss)}
                for k, g in grads.items():
                    flags["grad:" + k] = jnp.isfinite(g).all()
                out = out + (flags,)
            if health:
                # FLAGS_train_health_every: per-layer health vectors as
                # side-outputs of the SAME program (always last element)
                out = out + (_layer_health_outputs(params, new_params,
                                                   grads),)
            return out

        return step

    # -- gradient merge (k-step accumulation) ------------------------------
    # reference: fleet/meta_optimizers/gradient_merge_optimizer.py — the
    # program rewrite that accumulates grads into persistent buffers and
    # gates the optimizer on step % k. TPU-native: two compiled programs
    # (accumulate-only and accumulate+update) over a donated accumulator
    # pytree; no cond divergence inside one program.
    def _make_accum_step(self, treedef):
        run = self._loss_and_grads(treedef)

        def step(params, buffers, acc, key, flat_batch):
            (loss, new_bufs), grads = run(params, buffers, key, flat_batch)
            new_acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            return new_bufs, new_acc, loss

        return step

    def _make_apply_step(self, treedef, check_finite=False, health=False):
        optimizer = self.optimizer
        k = self.grad_accum_steps
        avg = self.grad_accum_avg
        run = self._loss_and_grads(treedef)

        def step(params, buffers, opt_state, acc, lr, t, key, flat_batch):
            (loss, new_bufs), grads = run(params, buffers, key, flat_batch)
            total = jax.tree_util.tree_map(jnp.add, acc, grads)
            if avg:
                total = jax.tree_util.tree_map(lambda g: g / k, total)
            new_params, new_opt = optimizer.apply_gradients(
                params, total, opt_state, lr, t)
            zero = jax.tree_util.tree_map(jnp.zeros_like, acc)
            out = (new_params, new_bufs, new_opt, zero, loss)
            if check_finite:
                flags = {"loss": jnp.isfinite(loss)}
                for key_, g in total.items():
                    flags["grad:" + key_] = jnp.isfinite(g).all()
                out = out + (flags,)
            if health:
                # health rides the optimizer-update boundary only: the
                # MERGED gradient is the one the update consumed
                out = out + (_layer_health_outputs(params, new_params,
                                                   total),)
            return out

        return step

    # -- telemetry (paddle_tpu.monitor) ------------------------------------
    def _note_compile(self, kind: str, mon: bool, fr: bool = False):
        """A jit-cache miss: a new executable is about to be built. A miss
        for a program KIND that already has a compiled entry is a
        RECOMPILE (shape change, flag flip) — the event the scan-layer
        work exists to eliminate, surfaced here so it can't regress
        silently."""
        st = self._stats
        st["compiles"] += 1
        recompile = kind in self._kinds_compiled
        if recompile:
            st["recompiles"] += 1
        self._kinds_compiled.add(kind)
        if fr:
            from ..monitor.flight_recorder import get_flight_recorder
            get_flight_recorder().record_event(
                "recompile" if recompile else "compile", kind=kind,
                step=self.step_count)
        if mon:
            from ..monitor import get_registry
            reg = get_registry()
            reg.counter("train_step_compiles_total",
                        "TrainStep executable builds by program kind"
                        ).inc(kind=kind)
            if recompile:
                reg.counter("train_step_recompiles_total",
                            "TrainStep recompiles (new signature for an "
                            "already-compiled program kind)").inc(kind=kind)

    def _compile_program(self, kind: str, fn: Callable, donate_argnums,
                         example_args, mon: bool):
        """Build one program's executable AOT (``lower`` + ``compile``)
        so its cost/memory attribution comes from the SAME lowering and
        executable the step will run — one trace, one backend compile,
        exactly like the dispatch path, but with the ``Lowered`` and
        ``Compiled`` stages in hand for ``cost_analysis()`` /
        ``memory_analysis()`` (the dispatch path hides both). The
        lower/compile + sharding-drift self-heal machinery lives in
        :class:`paddle_tpu.jit.aot.AOTProgram` (shared with the serving
        engine's bucketed signatures)."""
        from ..monitor import goodput as _goodput
        from .aot import AOTProgram
        with _goodput.measure("compile"):
            return AOTProgram(
                kind, fn, donate_argnums=donate_argnums,
                on_attribute=lambda k, lowered, compiled:
                    self._attribute_program(k, lowered, compiled, mon),
            ).compile(example_args)

    def _attribute_program(self, kind: str, lowered, compiled, mon: bool):
        """Capture per-program FLOPs/bytes and the static HBM budget,
        register the budget process-wide, run the flag-gated OOM
        pre-flight, and (monitor on) publish attribution gauges."""
        from ..cost_model import CostModel
        from ..monitor import memory as monitor_memory
        entry = CostModel().attribute(lowered)
        pm = monitor_memory.analyze_compiled(compiled, kind=kind)
        if pm is not None:
            entry.update(peak_hbm_bytes=pm.peak_bytes,
                         argument_bytes=pm.argument_bytes,
                         output_bytes=pm.output_bytes,
                         temp_bytes=pm.temp_bytes,
                         generated_code_bytes=pm.generated_code_bytes)
            self._program_memory[kind] = pm
            monitor_memory.record_program(pm)
        self._programs[kind] = entry
        if mon:
            from ..monitor import get_registry
            reg = get_registry()
            reg.gauge("train_step_program_flops",
                      "static FLOPs per execution by program kind "
                      "(lowered.cost_analysis)").set(entry["flops"],
                                                     kind=kind)
            reg.gauge("train_step_program_bytes_accessed",
                      "static bytes accessed per execution by program "
                      "kind").set(entry["bytes_accessed"], kind=kind)
            if pm is not None:
                reg.gauge("train_step_program_peak_hbm_bytes",
                          "static peak-HBM estimate by program kind "
                          "(compiled.memory_analysis)"
                          ).set(pm.peak_bytes, kind=kind)
        if pm is not None:
            # OOM pre-flight BEFORE step 1 touches real capacity;
            # no-op unless FLAGS_memory_preflight is set
            monitor_memory.preflight_check(pm)

    def _record_step_metrics(self, t_wall: float, dispatch_s: float,
                             kind: str = "step"):
        from ..monitor import get_registry
        wall = time.perf_counter() - t_wall
        # per-kind wall EMA feeds the stats() MFU gauge (monitor-mode
        # only; meaningful when the loop blocks per step, as bench does)
        prev = self._wall_ema.get(kind)
        self._wall_ema[kind] = wall if prev is None \
            else 0.8 * prev + 0.2 * wall
        reg = get_registry()
        # goodput metrics ride the same monitor-mode publish cadence
        from ..monitor import goodput as _goodput
        led = _goodput.active_ledger()
        if led is not None:
            led.publish(reg)
        reg.counter("train_step_steps_total",
                    "TrainStep calls by program kind").inc(kind=kind)
        reg.histogram("train_step_dispatch_seconds",
                      "time for the jitted call to return (async XLA "
                      "dispatch)").observe(dispatch_s, kind=kind)
        reg.histogram("train_step_wall_seconds",
                      "full TrainStep.__call__ wall time (host prep + "
                      "dispatch)").observe(wall, kind=kind)
        # live-plane MFU: the same flops/(wall·peak) arithmetic stats()
        # computes on demand, published as a gauge so /metrics scrapers
        # and monitor_top see utilization without calling stats().
        # Absent on unknown chips (CPU test backend: peak is None).
        peak = self._peak_flops_cache
        if peak is None:
            try:
                from ..cost_model import device_peak_flops
                peak = device_peak_flops()
            except Exception:
                peak = 0.0
            self._peak_flops_cache = peak or 0.0
        flops = self._programs.get(kind, {}).get("flops")
        if peak and flops:
            reg.gauge("train_step_mfu",
                      "model FLOPs utilization by program kind (wall "
                      "EMA vs chip peak)").set(
                flops / (self._wall_ema[kind] * peak), kind=kind)

    def _publish_health(self, hvec, mon: bool):
        """Host side of the per-layer health pipeline, every
        FLAGS_train_health_every optimizer steps: read the f32 scalars
        back (the ONLY extra device sync of the feature, at publish
        cadence), publish train_layer_* gauges (monitor mode), run the
        EWMA spike detector, tail-mark the step trace and feed the
        flight recorder on a spike."""
        from ..monitor import goodput as _goodput
        host = {layer: {k: float(v) for k, v in vals.items()}
                for layer, vals in hvec.items()}
        _goodput.note_layer_health(host, step=self.step_count)
        if self._health_mon is None:
            self._health_mon = _goodput.LayerHealthMonitor()
        spikes = self._health_mon.observe(host)
        if mon:
            from ..monitor import get_registry
            reg = get_registry()
            g = reg.gauge("train_layer_grad_norm",
                          "per-layer gradient L2 norm (f32 side-output "
                          "of the compiled step; "
                          "FLAGS_train_health_every)")
            p = reg.gauge("train_layer_param_norm",
                          "per-layer post-update parameter L2 norm")
            u = reg.gauge("train_layer_update_ratio",
                          "per-layer ||update|| / ||param|| — the "
                          "classic learning-rate health signal")
            for layer, vals in host.items():
                g.set(vals["grad_norm"], layer=layer)
                p.set(vals["param_norm"], layer=layer)
                u.set(vals["update_ratio"], layer=layer)
        if spikes:
            self._stats["health_spikes"] += len(spikes)
            from ..monitor import trace as trace_mod
            cur = trace_mod.current_trace()
            if cur is not None:
                cur.mark_anomaly("health_spike", step=self.step_count,
                                 layers=sorted(spikes))
            if mon:
                from ..monitor import get_registry
                ctr = get_registry().counter(
                    "train_health_spikes_total",
                    "per-layer grad-norm EWMA spike detections")
                for layer in spikes:
                    ctr.inc(layer=layer)
            from ..monitor.flight_recorder import safe_record_event
            safe_record_event("health_spike", step=self.step_count,
                              layers=sorted(spikes))

    #: _step_span RecordEvent name -> structured-trace span name (the
    #: step-trace taxonomy of docs/OBSERVABILITY.md: dispatch /
    #: grad_accum_sync; collective::<op> and checkpoint.commit attach
    #: through the same maybe_span seam from their own modules)
    _TRACE_SPAN_NAMES = {"TrainStep.step": "dispatch",
                         "TrainStep.accum_microstep": "dispatch",
                         "TrainStep.grad_accum_sync": "grad_accum_sync"}

    @contextlib.contextmanager
    def _step_span(self, mon: bool, name: str = "TrainStep.step"):
        """RecordEvent around the dispatch in monitor mode — steps appear
        on host timelines next to the comm/op lanes — and, when a
        structured step trace is active (FLAGS_trace), the matching
        child span."""
        from ..monitor import trace as trace_mod
        if not mon:
            with trace_mod.maybe_span(
                    self._TRACE_SPAN_NAMES.get(name, name)):
                yield
            return
        from ..profiler import RecordEvent
        with RecordEvent(name), trace_mod.maybe_span(
                self._TRACE_SPAN_NAMES.get(name, name)):
            yield

    def _watchdog(self, loss, prev_params, prev_buffers, key, flat,
                  treedef, step_index: int, step_kind: str = "step",
                  rollback=None):
        """check_numerics post-step check (eager, outside the compiled
        step). Cost while healthy: ONE scalar readback per step (which
        also synchronizes dispatch — this is a debugging mode). On a trip:
        a grads-only diagnosis pass re-runs fwd+bwd at the PRE-update
        state with the same RNG key and batch, naming the first (sorted)
        non-finite gradient/parameter. ``step_kind`` disambiguates the
        two step clocks: accum-only trips report the MICROSTEP index,
        optimizer-update trips the step (optimizer) index.

        With ``skip_nonfinite_budget`` set, a trip within the budget
        calls ``rollback`` (restoring the pre-step state the caller
        captured) and returns instead of raising; the trip still lands
        in the stats, registry and flight recorder as a
        ``nonfinite_skip`` event. The budget counts CONSECUTIVE skips —
        any finite step resets it — and exhaustion raises
        :class:`NonFiniteError` whatever the check_numerics action is."""
        if bool(jnp.isfinite(loss).all()):
            self._consecutive_skips = 0
            return
        # goodput: a rolled-back step made no progress — move its
        # dispatch seconds out of productive_dispatch and attribute the
        # whole trip handling (diagnosis pass, rollback) to
        # nonfinite_rollback
        from ..monitor import goodput as _goodput
        led = _goodput.active_ledger()
        if led is None:
            return self._watchdog_trip(loss, prev_params, prev_buffers,
                                       key, flat, treedef, step_index,
                                       step_kind, rollback)
        led.reattribute_last("nonfinite_rollback")
        with led.measure("nonfinite_rollback"):
            return self._watchdog_trip(loss, prev_params, prev_buffers,
                                       key, flat, treedef, step_index,
                                       step_kind, rollback)

    def _watchdog_trip(self, loss, prev_params, prev_buffers, key, flat,
                       treedef, step_index: int, step_kind: str,
                       rollback):
        self._stats["nonfinite_trips"] += 1
        from ..monitor import trace as trace_mod
        cur_trace = trace_mod.current_trace()
        if cur_trace is not None:
            # tail-retain the step trace even when the trip is handled
            # (warn mode / within skip_nonfinite_budget — no raise)
            cur_trace.mark_anomaly("nonfinite", step=step_index,
                                   step_kind=step_kind)
        from ..monitor import get_registry
        from ..monitor.numerics import NonFiniteError, first_nonfinite
        # the param scan needs no compilation — run it before (and
        # independently of) the fallible grads re-trace
        bad_param = bad_grad = None
        try:
            bad_param = first_nonfinite(prev_params)
        except Exception:
            pass
        try:
            sig = ("diag", _sig_of(flat)[0], treedef)
            diag = self._jitted.get(sig)
            if diag is None:
                diag = jax.jit(self._loss_and_grads(treedef))
                self._jitted[sig] = diag
            (_dloss, _dbufs), grads = diag(prev_params, prev_buffers, key,
                                           flat)
            bad_grad = first_nonfinite(grads)
        except Exception:
            pass                      # diagnosis is best-effort
        get_registry().counter(
            "numerics_nonfinite_total",
            "NaN/Inf watchdog trips by kind").inc(what="train_step")
        parts = [f"non-finite loss at {step_kind} {step_index}"]
        if bad_param is not None:
            parts.append(f"parameter {bad_param!r} was already non-finite "
                         "before this step")
        if bad_grad is not None:
            parts.append(f"first non-finite gradient: {bad_grad!r}")
        msg = ("; ".join(parts)
               + " (TrainStep check_numerics watchdog; the in-graph "
               "variant is FLAGS_check_nan_inf)")
        offender = bad_grad or bad_param or "loss"
        from ..monitor import flight_recorder as _flight
        budget = self.skip_nonfinite_budget
        if budget and rollback is not None:
            self._consecutive_skips += 1
            if self._consecutive_skips <= budget:
                # within budget: revert the whole update and continue —
                # the GradScaler skip model generalized to any
                # non-finite trip. The event is recorded everywhere a
                # post-mortem would look, but the run lives.
                rollback()
                self._stats["nonfinite_skips"] += 1
                get_registry().counter(
                    "nonfinite_skips_total",
                    "non-finite steps skipped under "
                    "skip_nonfinite_budget").inc()
                if _flight.enabled():
                    _flight.get_flight_recorder().record_event(
                        "nonfinite_skip", step=step_index,
                        step_kind=step_kind, offender=offender,
                        consecutive=self._consecutive_skips,
                        budget=budget)
                import warnings
                warnings.warn(
                    msg + f"; update skipped and rolled back "
                    f"({self._consecutive_skips}/{budget} consecutive)",
                    RuntimeWarning, stacklevel=3)
                return
            # exhaustion: roll back too before raising — a supervisor
            # that catches NonFiniteError and checkpoints for handoff
            # must persist the last-known-good state, not the NaN update
            # every within-budget trip carefully reverted
            rollback()
            msg += (f"; skip_nonfinite_budget exhausted "
                    f"({budget} consecutive non-finite steps; state "
                    "rolled back to the last finite step)")
        # crash forensics: a watchdog trip dumps the flight recorder
        # (ring of recent steps + fingerprint), naming the trip step —
        # best-effort, the NonFiniteError below must win
        dump_path = _flight.trip_dump(step=step_index,
                                      reason="nan_watchdog",
                                      offender=offender,
                                      step_kind=step_kind)
        if dump_path:
            msg += f"; flight recorder dump: {dump_path}"
        if self._check_numerics == "warn" and not (
                budget and self._consecutive_skips > budget):
            import warnings
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
            return
        raise NonFiniteError(msg, offender=offender, step=step_index)

    def stats(self) -> dict:
        """Telemetry snapshot since construction: our jit-entry builds
        (``compiles``/``recompiles`` — a warm scan-layer GPT shows exactly
        1 and 0), XLA backend-compile / persistent-cache / trace deltas
        (process-wide window, via utils.compilation), eager op-cache hit
        rates, accumulation/watchdog counters, and per-program-kind
        attribution under ``programs``: static flops / bytes_accessed /
        arithmetic_intensity (lowered.cost_analysis) and the
        ``peak_hbm_bytes`` budget (compiled.memory_analysis), plus an
        ``mfu`` gauge when the chip's peak FLOP/s is known and monitor
        mode has a wall-time EMA for the kind (None otherwise — e.g. the
        CPU test backend). Plain-dict reads — no device sync, callable
        every step."""
        from ..core.tensor import eager_cache_stats
        from ..utils.compilation import compile_counts
        cc = compile_counts()
        ec = eager_cache_stats()
        d = dict(self._stats)
        try:
            from ..cost_model import device_peak_flops
            peak = device_peak_flops()
        except Exception:
            peak = None
        programs = {}
        for kind, entry in self._programs.items():
            e = dict(entry)
            wall = self._wall_ema.get(kind)
            e["mfu"] = (e["flops"] / (wall * peak)
                        if peak and wall and e.get("flops") else None)
            programs[kind] = e
        d["programs"] = programs
        d.update(
            steps=self.step_count,
            microsteps=self._micro_count,
            grad_accum_steps=self.grad_accum_steps,
            backend_compiles=(cc["backend_compiles"]
                              - self._cc0["backend_compiles"]),
            persistent_cache_misses=(cc["cache_misses"]
                                     - self._cc0["cache_misses"]),
            jaxpr_traces=cc["jaxpr_traces"] - self._cc0["jaxpr_traces"],
            eager_cache_hits=ec["hits"] - self._ec0["hits"],
            eager_cache_misses=ec["misses"] - self._ec0["misses"],
        )
        seen = d["eager_cache_hits"] + d["eager_cache_misses"]
        d["eager_cache_hit_rate"] = (d["eager_cache_hits"] / seen
                                     if seen else None)
        # the goodput ledger view, so single-process trainers (and the
        # /statusz TrainStep.stats() section) see it without the admin
        # plane; absent with FLAGS_train_goodput off
        from ..monitor import goodput as _goodput
        led = _goodput.get_ledger()
        if led is not None and _goodput.active():
            d["goodput"] = led.snapshot()
        return d

    def _call_accum(self, flat, treedef, check, mon, fr, t_wall):
        """Gradient-merge path: k-1 accumulate-only microsteps, then one
        accumulate+update microstep."""
        from ..core.flags import get_flag
        if self._acc_grads is None:
            self._acc_grads = jax.tree_util.tree_map(
                jnp.zeros_like, self.params)
        key = make_rng("train_step")
        self._micro_count += 1
        watch = bool(self._check_numerics) or self.skip_nonfinite_budget > 0
        prev = ((self.params, self.buffers, self._acc_grads,
                 self.opt_state) if watch else None)
        is_update = self._micro_count % self.grad_accum_steps == 0
        if not is_update:
            sig = ("acc", _sig_of(flat)[0], treedef)
            jitted = self._jitted.get(sig)
            if jitted is None:
                self._note_compile("accum", mon, fr)
                fn = self._make_accum_step(treedef)
                # _donation_safe re-checked per compiled entry: the
                # persistent cache may be enabled after construction
                jitted = self._compile_program(
                    "accum", fn,
                    (2,) if self._donate and _donation_safe() else (),
                    (self.params, self.buffers, self._acc_grads, key,
                     flat), mon)
                self._jitted[sig] = jitted
            from ..monitor import goodput as _goodput
            t0 = time.perf_counter() if mon else 0.0
            with _control_flow_guidance(), self._step_span(
                    mon, "TrainStep.accum_microstep"), \
                    _goodput.measure("productive_dispatch",
                                     on_error="host_other"):
                self.buffers, self._acc_grads, loss = self._dispatch(
                    jitted, self.params, self.buffers, self._acc_grads,
                    key, flat)
            dispatch_s = time.perf_counter() - t0 if mon else None
            if _chaos.active() and _chaos.probe("grad.nonfinite"):
                loss = jnp.full_like(loss, jnp.nan)
            if mon:
                self._record_step_metrics(t_wall, dispatch_s,
                                          kind="accum")
            if fr:
                from ..monitor.flight_recorder import get_flight_recorder
                get_flight_recorder().record_step(
                    self._micro_count, loss=loss, kind="accum",
                    dispatch_ms=None if dispatch_s is None
                    else dispatch_s * 1e3)
            if watch:
                def rollback():
                    (self.params, self.buffers, self._acc_grads,
                     self.opt_state) = prev
                    self._micro_count -= 1
                self._watchdog(loss, prev[0], prev[1], key, flat, treedef,
                               self._micro_count, step_kind="microstep",
                               rollback=rollback)
            return Tensor(loss)
        self.step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.step_count, jnp.int32)
        health_every = int(get_flag("train_health_every") or 0)
        health = health_every > 0
        sig = ("apply", _sig_of(flat)[0], treedef, check, health)
        jitted = self._jitted.get(sig)
        if jitted is None:
            self._note_compile("apply", mon, fr)
            fn = self._make_apply_step(treedef, check_finite=check,
                                       health=health)
            jitted = self._compile_program(
                "apply", fn,
                (0, 2, 3) if self._donate and _donation_safe() else (),
                (self.params, self.buffers, self.opt_state,
                 self._acc_grads, lr, t, key, flat), mon)
            self._jitted[sig] = jitted
        from ..monitor import goodput as _goodput
        t0 = time.perf_counter() if mon else 0.0
        with _control_flow_guidance(), self._step_span(
                mon, "TrainStep.grad_accum_sync"), \
                _goodput.measure("productive_dispatch",
                                 on_error="host_other"):
            out = self._dispatch(jitted, self.params, self.buffers,
                                 self.opt_state, self._acc_grads, lr, t,
                                 key, flat)
        # the k-th microstep is the accumulation SYNC boundary: grads are
        # folded into the optimizer here (reference: the gated update
        # block of gradient_merge_optimizer.py)
        self._stats["grad_accum_syncs"] += 1
        dispatch_s = time.perf_counter() - t0 if mon else None
        if mon:
            self._record_step_metrics(t_wall, dispatch_s, kind="apply")
            from ..monitor import get_registry
            get_registry().counter(
                "train_step_grad_accum_syncs_total",
                "gradient-accumulation optimizer-update boundaries").inc()
        hvec = None
        if health:
            hvec, out = out[-1], out[:-1]
        if check:
            (self.params, self.buffers, self.opt_state, self._acc_grads,
             loss, flags) = out
            bad = [k_ for k_, ok in flags.items() if not bool(ok)]
            if bad:
                raise RuntimeError(
                    f"NaN/Inf detected at step {self.step_count} in: "
                    f"{', '.join(sorted(bad))} (FLAGS_check_nan_inf)")
        else:
            (self.params, self.buffers, self.opt_state, self._acc_grads,
             loss) = out
        if hvec is not None and self.step_count % health_every == 0:
            self._publish_health(hvec, mon)
        if _chaos.active() and _chaos.probe("grad.nonfinite"):
            loss = jnp.full_like(loss, jnp.nan)
        if fr:
            from ..monitor.flight_recorder import get_flight_recorder
            get_flight_recorder().record_step(
                self.step_count, loss=loss, kind="apply",
                wall_ms=(time.perf_counter() - t_wall) * 1e3 if mon
                else None,
                dispatch_ms=None if dispatch_s is None
                else dispatch_s * 1e3)
        if watch:
            def rollback():
                (self.params, self.buffers, self._acc_grads,
                 self.opt_state) = prev
                self._micro_count -= 1
                self.step_count -= 1
            self._watchdog(loss, prev[0], prev[1], key, flat, treedef,
                           self.step_count, rollback=rollback)
        return Tensor(loss)

    def __call__(self, *batch):
        from ..monitor import trace as trace_mod
        if not trace_mod.enabled():
            return self._call_impl(*batch)
        # one trace per step: dispatch / grad-accum sync spans attach
        # inside, eager collectives and checkpoint commits through the
        # activate() context. A non-finite trip tail-retains the trace
        # whatever FLAGS_trace_sample said.
        tr = trace_mod.get_tracer().start_trace(
            "train.step", step=self.step_count + 1)
        # the wait for THIS step's batch happened before the trace
        # existed — attach it retroactively with explicit timestamps
        # (same perf_counter clock) so where-did-the-time-go reads on
        # one timeline: data_wait → dispatch → sync
        from ..monitor import goodput as _goodput
        led = _goodput.get_ledger()
        if led is not None and _goodput.active():
            dw = led.pop_pending_data_wait()
            if dw is not None:
                sp = tr.start_span("data_wait", t=dw[0])
                tr.end_span(sp, t=dw[1])
        try:
            with trace_mod.activate(tr):
                return self._call_impl(*batch)
        except BaseException as e:
            from ..monitor.numerics import NonFiniteError
            tr.mark_anomaly(
                "nonfinite" if isinstance(e, NonFiniteError)
                else "failed", error=f"{type(e).__name__}: {e}")
            raise
        finally:
            trace_mod.get_tracer().finish_trace(tr)

    def _call_impl(self, *batch):
        from ..core.flags import get_flag
        mon = bool(get_flag("monitor"))
        t_wall = time.perf_counter() if mon else 0.0
        raw = [b._data if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        raw = self._place_batch(raw)
        flat, treedef = jax.tree_util.tree_flatten(raw)
        check = bool(get_flag("check_nan_inf"))
        fr = mon or bool(get_flag("flight_recorder"))
        if self.grad_accum_steps > 1:
            return self._call_accum(flat, treedef, check, mon, fr, t_wall)
        self.step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.step_count, jnp.int32)
        key = make_rng("train_step")
        # health folds into the jit-cache signature: flag off keeps the
        # exact program (and dispatch args) of every prior PR — the
        # zero-overhead pin; flag on only ADDS f32 scalar outputs
        health_every = int(get_flag("train_health_every") or 0)
        health = health_every > 0
        sig = (_sig_of(flat)[0], treedef, check, health)
        jitted = self._jitted.get(sig)
        if jitted is None:
            self._note_compile("step", mon, fr)
            fn = self._make_step(treedef, check_finite=check,
                                 health=health)
            donate = (0, 2) if self._donate and _donation_safe() else ()
            jitted = self._compile_program(
                "step", fn, donate,
                (self.params, self.buffers, self.opt_state, lr, t, key,
                 flat), mon)
            self._jitted[sig] = jitted
        watch = bool(self._check_numerics) or self.skip_nonfinite_budget > 0
        prev = ((self.params, self.buffers, self.opt_state) if watch
                else None)
        from ..monitor import goodput as _goodput
        t0 = time.perf_counter() if mon else 0.0
        with _control_flow_guidance(), self._step_span(mon), \
                _goodput.measure("productive_dispatch",
                                 on_error="host_other"):
            out = self._dispatch(jitted, self.params, self.buffers,
                                 self.opt_state, lr, t, key, flat)
        dispatch_s = time.perf_counter() - t0 if mon else None
        if mon:
            self._record_step_metrics(t_wall, dispatch_s)
        hvec = None
        if health:
            hvec, out = out[-1], out[:-1]
        if check:
            self.params, self.buffers, self.opt_state, loss, flags = out
            bad = [k for k, ok in flags.items() if not bool(ok)]
            if bad:
                raise RuntimeError(
                    f"NaN/Inf detected at step {self.step_count} in: "
                    f"{', '.join(sorted(bad))} (FLAGS_check_nan_inf)")
        else:
            self.params, self.buffers, self.opt_state, loss = out
        if hvec is not None and self.step_count % health_every == 0:
            self._publish_health(hvec, mon)
        if _chaos.active() and _chaos.probe("grad.nonfinite"):
            loss = jnp.full_like(loss, jnp.nan)
        if fr:
            from ..monitor.flight_recorder import get_flight_recorder
            get_flight_recorder().record_step(
                self.step_count, loss=loss, kind="step",
                wall_ms=(time.perf_counter() - t_wall) * 1e3 if mon
                else None,
                dispatch_ms=None if dispatch_s is None
                else dispatch_s * 1e3)
        if watch:
            def rollback():
                self.params, self.buffers, self.opt_state = prev
                self.step_count -= 1
            self._watchdog(loss, prev[0], prev[1], key, flat, treedef,
                           self.step_count, rollback=rollback)
        return Tensor(loss)

    def sync_to_layer(self):
        merged = {**self.frozen, **self.params}
        for k, p in self.layer.named_parameters():
            if k in merged:
                p._data = merged[k]
        for k, b in self.layer.named_buffers():
            if k in self.buffers:
                b._data = self.buffers[k]

    # -- checkpoint/resume -------------------------------------------------
    def state_dict(self):
        """Full training state: params + frozen + buffers + optimizer slots
        + step count + RNG, enough to resume bit-exactly (reference:
        framework/io.py:553 save of model+opt state; SURVEY §5 resume)."""
        import numpy as np

        from ..core.random import default_generator

        def host(tree):
            return jax.tree_util.tree_map(
                lambda a: np.asarray(a) if hasattr(a, "shape") else a, tree)

        return {
            "params": host(self.params),
            "frozen": host(self.frozen),
            "buffers": host(self.buffers),
            "opt_state": host(self.opt_state),
            "step_count": self.step_count,
            "rng_state": default_generator().get_state(),
            "lr": self.optimizer.get_lr(),
        }

    def set_state_dict(self, state):
        """Restore a state_dict; re-applies SPMD layouts when a mesh is
        active so resume preserves shardings."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..core.random import default_generator

        def put(k, a, spec=None):
            if not hasattr(a, "shape"):
                return a
            if self.mesh is not None:
                return jax.device_put(
                    a, NamedSharding(self.mesh, spec or P()))
            return jnp.asarray(a)

        if self.mesh is not None:
            self._specs = self._param_specs()
            self.params = {k: put(k, v, self._specs.get(k))
                           for k, v in state["params"].items()}
            self.opt_state = {
                k: jax.tree_util.tree_map(
                    lambda a, k=k: jax.device_put(
                        a, NamedSharding(self.mesh,
                                         self._slot_spec(k, a.shape)))
                    if hasattr(a, "shape") and getattr(a, "ndim", 0) > 0
                    else a, v)
                for k, v in state["opt_state"].items()}
        else:
            self.params = {k: jnp.asarray(v)
                           for k, v in state["params"].items()}
            self.opt_state = jax.tree_util.tree_map(
                lambda a: jnp.asarray(a) if hasattr(a, "shape") else a,
                state["opt_state"])
        if self.mesh is not None:
            from jax.sharding import NamedSharding as _NS
            frozen_specs = {k: getattr(p, "spec", None) or P()
                            for k, p in self.layer.named_parameters()
                            if k not in self.params}
            self.frozen = {
                k: jax.device_put(v, _NS(self.mesh,
                                         frozen_specs.get(k, P())))
                for k, v in state["frozen"].items()}
            self.buffers = {k: jax.device_put(v, _NS(self.mesh, P()))
                            for k, v in state["buffers"].items()}
        else:
            self.frozen = {k: jnp.asarray(v)
                           for k, v in state["frozen"].items()}
            self.buffers = {k: jnp.asarray(v)
                            for k, v in state["buffers"].items()}
        self.step_count = int(state["step_count"])
        # restore starts a fresh gradient-accumulation window: a partial
        # accumulator from before the restore must never leak in
        self._acc_grads = None
        self._micro_count = 0
        if state.get("rng_state") is not None:
            default_generator().set_state(state["rng_state"])
        if state.get("lr") is not None and hasattr(self.optimizer, "set_lr"):
            try:
                self.optimizer.set_lr(state["lr"])
            except Exception:
                pass
        self.sync_to_layer()

    def save(self, path: str):
        from ..framework.io import save as fsave
        fsave(self.state_dict(), path)

    def load(self, path: str):
        from ..framework.io import load as fload
        self.set_state_dict(fload(path))

    def save_sharded(self, path: str, asynchronous: bool = True):
        """Sharded async checkpoint (each host writes its own shards;
        serialization overlaps training). See distributed.checkpoint."""
        from ..distributed import checkpoint as dckpt
        dckpt.save_train_step(self, path, asynchronous=asynchronous)

    def load_sharded(self, path: str):
        """Restore a sharded checkpoint, resharding to this step's current
        mesh layout (which may differ from the one saved under)."""
        from ..distributed import checkpoint as dckpt
        dckpt.load_train_step(self, path)


def save(layer, path, input_spec=None, **configs):
    """Export: StableHLO text + params pickle (replaces save_inference_model).

    reference: python/paddle/fluid/dygraph/jit.py save / io.py:1246.
    """
    import os
    import pickle

    import numpy as np

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    params = param_arrays(layer)
    buffers = buffer_arrays(layer)
    meta = {"class": type(layer).__name__}

    if input_spec:
        specs = [s if isinstance(s, InputSpec) else InputSpec(s) for s in input_spec]
        example = [jnp.zeros(tuple(d if d and d > 0 else 1 for d in s.shape),
                             s.dtype) for s in specs]

        def pure(p, b, *inputs):
            tensors = [Tensor(i) for i in inputs]
            with bind(layer, p, dict(b)), no_grad(), trace_rng(jax.random.key(0)):
                out = layer(*tensors)
            return unwrap(out)

        was_training = layer.training
        layer.eval()
        try:
            jitted = jax.jit(pure)
            lowered = jitted.lower(params, buffers, *example)
            stablehlo = lowered.as_text(dialect="stablehlo")
            # portable executable blob: params/buffers are BAKED as the
            # first two arguments; load() rebinds the pickled values
            from jax import export as jexport
            exp = jexport.export(jitted)(
                jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
                jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
                *[jax.ShapeDtypeStruct(e.shape, e.dtype) for e in example])
            with open(path + ".jaxexport", "wb") as f:
                f.write(exp.serialize())
        finally:
            if was_training:
                layer.train()
        with open(path + ".mlir", "w") as f:
            f.write(stablehlo)
        meta["input_spec"] = [(tuple(s.shape), str(np.dtype(s.dtype))) for s in specs]

    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in {**params, **buffers}.items()}, f)
    with open(path + ".pdmodel.meta", "wb") as f:
        pickle.dump({**meta, "param_names": list(params),
                     "buffer_names": list(buffers)}, f)


class TranslatedLayer:
    """Runnable model restored from a jit.save export (reference:
    fluid/dygraph/io.py TranslatedLayer / jit.py:1162 TracedLayer): holds
    the deserialized executable + parameter arrays and is called like the
    original layer (positional Tensors/arrays in, Tensor out)."""

    def __init__(self, exported, params, buffers, meta):
        self._exported = exported
        self._params = params
        self._buffers = buffers
        self._meta = meta

    @property
    def program(self):   # parity shim: the export object is the "program"
        return self._exported

    def state_dict(self):
        return {**self._params, **self._buffers}

    def __call__(self, *inputs, **feeds):
        if feeds and inputs:
            raise TypeError("pass inputs positionally OR as named feeds, "
                            "not both")
        if feeds:
            # Executor.run feeds by name: exports name inputs 'x0','x1',...
            n_in = len(self._meta.get("input_spec") or []) or len(feeds)

            def idx(n):
                if not (n.startswith("x") and n[1:].isdigit()):
                    raise KeyError(
                        f"unknown feed {n!r}: a jit.save export names its "
                        f"inputs positionally as "
                        f"{['x%d' % i for i in range(n_in)]}")
                return int(n[1:])
            inputs = [feeds[k] for k in sorted(feeds, key=idx)]
        raw = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        out = self._exported.call(self._params, self._buffers, *raw)
        if isinstance(out, (tuple, list)):
            outs = [Tensor(o) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)


def load(path, **configs):
    """Restore a jit.save export.

    Returns a runnable :class:`TranslatedLayer` when the executable blob
    exists (saved with input_spec); otherwise the raw params dict
    (weights-only save). reference: fluid/io.py:1246 load_inference_model."""
    import os
    import pickle

    with open(path + ".pdiparams", "rb") as f:
        arrays = pickle.load(f)
    if not os.path.exists(path + ".jaxexport"):
        return arrays
    with open(path + ".pdmodel.meta", "rb") as f:
        meta = pickle.load(f)
    with open(path + ".jaxexport", "rb") as f:
        from jax import export as jexport
        exported = jexport.deserialize(f.read())
    params = {k: jnp.asarray(arrays[k]) for k in meta.get("param_names", [])}
    buffers = {k: jnp.asarray(arrays[k])
               for k in meta.get("buffer_names", [])}
    return TranslatedLayer(exported, params, buffers, meta)
