"""InputSpec (reference: python/paddle/static/input.py InputSpec)."""

from __future__ import annotations

from ..core import dtypes


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
