"""AOT-compiled program signatures: ``jit.lower().compile()`` with a
self-healing re-lower on input-sharding drift.

Shared by the two places that build long-lived executables ahead of
dispatch and need the ``Lowered``/``Compiled`` stages in hand:

- :class:`~paddle_tpu.jit.to_static.TrainStep` — per-program-kind
  cost/memory attribution (``lowered.cost_analysis()`` /
  ``compiled.memory_analysis()``, PR 4);
- the serving engine (:mod:`paddle_tpu.serving.engine`) — prefill/decode
  programs compiled per bucketed signature at warmup, so the first
  request never pays a trace+compile and the bucket table bounds the
  executable count.

Why not plain ``jax.jit``: dispatch-mode jit hides both stages and
compiles lazily at first call; an AOT ``Compiled`` exposes them but
REFUSES input layouts/shardings that drift from the example arguments
(e.g. ZeRO: XLA re-shards updated params over the zero axis, so step 2's
inputs no longer match step 1's executable — dispatch-mode jit silently
recompiles there). :class:`AOTProgram` does the same healing explicitly:
re-lower/re-compile on the mismatch ValueError, and after repeated
flip-flops hand the entry to dispatch-mode jit, whose executable cache
holds every layout at once.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax

__all__ = ["AOTProgram"]


class AOTProgram:
    """One program signature, compiled ahead of time.

    ``on_attribute(kind, lowered, compiled)`` is called after every
    successful build (including heals — newest wins), with the exact
    lowering and executable the calls will run; attribution therefore
    costs no extra trace or compile.  When the AOT stage is unavailable
    (exotic backend/version), calls fall back to dispatch-mode jit and
    ``aot_available`` is False — the program still runs, attribution is
    skipped.
    """

    #: layout flip-flops tolerated under one shape signature before the
    #: entry is handed to dispatch-mode jit for good
    MAX_HEALS = 2

    def __init__(self, kind: str, fn: Callable,
                 donate_argnums: Sequence[int] = (),
                 on_attribute: Optional[Callable[[str, Any, Any], None]]
                 = None):
        self.kind = kind
        self.donate_argnums = tuple(donate_argnums)
        self._jitted = jax.jit(fn, donate_argnums=self.donate_argnums)
        self._on_attribute = on_attribute
        self._compiled: Any = None
        self.heals = 0
        self.builds = 0

    # -- construction ------------------------------------------------------
    def _build(self, args) -> Any:
        """lower+compile for ``args``; None when the AOT stage is
        unavailable (the dispatch path still runs the program)."""
        from .to_static import _control_flow_guidance
        with _control_flow_guidance():
            lowered = self._jitted.lower(*args)
        try:
            compiled = lowered.compile()
        except Exception:
            return None
        self.builds += 1
        if self._on_attribute is not None:
            self._on_attribute(self.kind, lowered, compiled)
        return compiled

    def compile(self, example_args) -> "AOTProgram":
        """Build the executable for the example signature (idempotent on
        success; a failed AOT stage leaves the dispatch fallback)."""
        self._compiled = self._build(example_args)
        return self

    @property
    def aot_available(self) -> bool:
        return self._compiled is not None

    # -- dispatch ----------------------------------------------------------
    def __call__(self, *args):
        if self._compiled is None:
            return self._jitted(*args)
        try:
            return self._compiled(*args)
        except ValueError as e:
            if "Compiled object called with" not in str(e):
                raise
            # Input shardings/layouts moved since this signature was
            # compiled — the drift dispatch-mode jit silently recompiles
            # through. Heal the same way, re-attributing from the new
            # executable. The mismatch is detected BEFORE execution, so
            # donated args are intact.
            self.heals += 1
            if self.heals > self.MAX_HEALS:
                # layouts keep flip-flopping under one shape signature:
                # hand the entry to dispatch-mode jit, whose executable
                # cache holds every layout at once
                self._compiled = None
                return self._jitted(*args)
            fresh = self._build(args)
            self._compiled = fresh
            if fresh is None:
                return self._jitted(*args)
            return fresh(*args)
