"""Functionalisation of Layers.

The TPU-native replacement for the reference's dygraph→static translator
(reference: python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:768):
instead of AST-rewriting python into ProgramDesc, we *bind* a Layer's
parameters/buffers to raw arrays (or tracers) for the duration of a call, so
ordinary forward() code traces under jax.jit unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional, Tuple

import jax

from ..core.random import trace_rng
from ..core.tensor import Tensor, no_grad


def named_params_and_buffers(layer) -> Tuple[Dict[str, Tensor], Dict[str, Tensor]]:
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    return params, buffers


def param_arrays(layer) -> Dict[str, jax.Array]:
    return {k: p._data for k, p in layer.named_parameters()}


def trainable_param_arrays(layer) -> Dict[str, jax.Array]:
    return {k: p._data for k, p in layer.named_parameters()
            if getattr(p, "trainable", True) and not p.stop_gradient}


def buffer_arrays(layer) -> Dict[str, jax.Array]:
    return {k: b._data for k, b in layer.named_buffers()}


@contextlib.contextmanager
def bind(layer, params: Optional[Dict[str, Any]] = None,
         buffers: Optional[Dict[str, Any]] = None):
    """Temporarily swap parameter/buffer storage with the given arrays.

    After the with-block, buffer entries in ``buffers`` are REFRESHED to the
    final (possibly traced) values so callers can thread running-stat updates
    through jit as pure state.
    """
    p_objs, b_objs = named_params_and_buffers(layer)
    saved_p = {k: t._data for k, t in p_objs.items()}
    saved_b = {k: t._data for k, t in b_objs.items()}
    try:
        if params:
            for k, arr in params.items():
                if k in p_objs:
                    p_objs[k]._data = arr
        if buffers:
            for k, arr in buffers.items():
                if k in b_objs:
                    b_objs[k]._data = arr
        yield
        if buffers is not None:
            for k, t in b_objs.items():
                if k in buffers:
                    buffers[k] = t._data
    finally:
        for k, t in p_objs.items():
            t._data = saved_p[k]
        for k, t in b_objs.items():
            t._data = saved_b[k]


def functional_call(layer, params: Dict[str, Any], *args, buffers=None,
                    rng=None, training: Optional[bool] = None, **kwargs):
    """Call layer.forward as a pure function of (params, buffers, rng, args).

    Returns (outputs, new_buffers). ``args`` may be raw arrays or Tensors;
    outputs are unwrapped to raw arrays (pytree).
    """
    wrapped = [Tensor(a) if not isinstance(a, Tensor) else a for a in args]
    buf = dict(buffers) if buffers is not None else buffer_arrays(layer)
    prev_training = layer.training
    if training is not None:
        layer.training = training
        for sub in layer.sublayers():
            sub.training = training
    key = rng if rng is not None else jax.random.key(0)
    try:
        with bind(layer, params, buf), no_grad(), trace_rng(key):
            out = layer(*wrapped, **kwargs)
    finally:
        if training is not None:
            layer.training = prev_training
            for sub in layer.sublayers():
                sub.training = prev_training
    return unwrap(out), buf


def unwrap(out):
    """Tensor pytree -> raw array pytree."""
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, tuple):
        return tuple(unwrap(o) for o in out)
    if isinstance(out, list):
        return [unwrap(o) for o in out]
    if isinstance(out, dict):
        return {k: unwrap(v) for k, v in out.items()}
    return out


def wrap(out):
    """Raw array pytree -> Tensor pytree."""
    if isinstance(out, jax.Array):
        return Tensor(out)
    if isinstance(out, tuple):
        return tuple(wrap(o) for o in out)
    if isinstance(out, list):
        return [wrap(o) for o in out]
    if isinstance(out, dict):
        return {k: wrap(v) for k, v in out.items()}
    return out
