"""Dygraph-to-static AST transform: python control flow over tensors.

reference parity: the dygraph_to_static AST translator
(reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:768, ifelse_transformer.py, loop_transformer.py,
break_continue_transformer.py, return_transformer.py) which rewrites
python control flow into conditional_block/while ops.

TPU-native redesign — four passes over the AST:

1. `_ForToWhile`: ``for i in range(...)`` becomes a counter while loop
   whose endpoints may be traced tensors (the reference's
   loop_transformer for-to-while); other iterables keep the python
   ``for`` (static-length tensor iteration unrolls fine under jit).
2. `_ReturnTransformer`: ``return`` inside control flow becomes a
   carried flag + value, with the statements after the returning block
   guarded and loop conditions extended (return_transformer.py).
3. `_BreakContinue`: ``break``/``continue`` become carried flags with
   guard-`if` chains and an extended loop condition
   (break_continue_transformer.py).
4. `_ControlFlowTransformer`: each ``if``/``while`` is functionalized
   into a call to a dispatch helper — `__jst_if__` / `__jst_while__` —
   passing the assigned variables as explicit arguments. At RUNTIME the
   helper checks the condition's type: a concrete python bool takes the
   normal python path (zero overhead, exact semantics); a traced Tensor
   routes to `static.nn.cond` / `while_loop` (lax.cond /
   lax.while_loop), the XLA-compilable form. `__jst_while__` re-checks
   per iteration, so a loop whose condition BECOMES traced mid-flight
   (a break flag set inside a lax.cond) hands off to lax.while_loop at
   that point.

Deliberately restricted (falls back to the untransformed statement or
the whole original function, where tracing's guided
ConcretizationTypeError explains the options): yield anywhere;
return inside try/finally or inside a non-range python for; scope
declarations (global/nonlocal) or import/def/class inside a branch.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Tuple

__all__ = ["ast_transform", "convert_to_static"]

class _Unbound:
    """Placeholder for a name with no binding before the control flow.
    Harmless to carry and reassign; USING it raises a clear NameError
    (mirroring python's unbound-local behavior)."""

    def __repr__(self):
        return "<unbound dy2static variable>"

    def _raise(self, *a, **k):
        raise NameError(
            "variable was only assigned inside control flow that did not "
            "execute; initialize it before the if/while")

    __bool__ = __getattr__ = __call__ = __add__ = __radd__ = __sub__ = \
        __mul__ = __iter__ = __len__ = __float__ = __int__ = _raise


# single sentinel instance shared by all transformed functions
_UNDEF = _Unbound()

_FN_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
              ast.ClassDef)


def _assigned_names(nodes) -> set:
    out = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
            elif isinstance(sub, ast.AugAssign) and \
                    isinstance(sub.target, ast.Name):
                out.add(sub.target.id)
    return out


def _has_scope_decl(nodes) -> bool:
    return any(isinstance(sub, (ast.Global, ast.Nonlocal))
               for n in nodes for sub in ast.walk(n))


def _has_nonname_binding(nodes) -> bool:
    """import / def / class statements bind names invisibly to the
    Name-store scan; functionalizing such a branch would trap the binding
    in the generated function's locals. Generated `__jst_*` dispatch fns
    are exempt — they are self-contained and re-defined per execution."""
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub.name.startswith("__jst_"):
                continue
            if isinstance(sub, (ast.Import, ast.ImportFrom,
                                ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                return True
    return False


def _contains(nodes, types, stop=()) -> bool:
    """Any node of `types` in `nodes`, not descending into nested fn
    scopes or `stop` node types (e.g. nested loops for break/continue).
    The top-level `nodes` themselves are always entered."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, types):
            return True
        if isinstance(n, _FN_SCOPES) or isinstance(n, stop):
            continue                      # don't descend
        stack.extend(ast.iter_child_nodes(n))
    return False


def _has_flow_escape(nodes) -> bool:
    """return/yield/break/continue that would escape this block (after
    passes 1-3 these only remain in untransformable shapes)."""
    return _contains(nodes, (ast.Return, ast.Yield, ast.YieldFrom,
                             ast.Break, ast.Continue))


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _assign(name, value):
    return ast.Assign(targets=[_store(name)], value=value)


def _const(v):
    return ast.Constant(value=v)


def _call(fname, *args):
    return ast.Call(func=_load(fname), args=list(args), keywords=[])


def _fn_def(name, args, body):
    fd = ast.FunctionDef(name=name, args=args, body=body,
                         decorator_list=[], returns=None)
    fd.type_params = []          # py3.12+ field
    return fd


def _undef_guard(name):
    """`try: name\nexcept NameError: name = __jst_undef__` — lets
    `if c: y = a else: y = b` work when y has no prior binding."""
    return ast.Try(
        body=[ast.Expr(value=_load(name))],
        handlers=[ast.ExceptHandler(
            type=_load("NameError"), name=None,
            body=[_assign(name, _load("__jst_undef__"))])],
        orelse=[], finalbody=[])


def _guard_if(flag_expr, body):
    """`if __jst_not__(<flag_expr>): <body>` — the statements following a
    flag-setting block, suppressed once the flag fires."""
    return ast.If(test=_call("__jst_not__", flag_expr), body=body,
                  orelse=[])


# ---------------------------------------------------------------------------
# Pass 1: for-over-range -> while (loop_transformer.py for->while)
# ---------------------------------------------------------------------------


class _ForToWhile(ast.NodeTransformer):
    """``for <name> in range(a[, b[, c]]):`` becomes a counter while loop
    so tensor-valued endpoints compile to lax.while_loop. Non-range
    iterables keep the python for: a static-length tensor unrolls under
    jit; python sequences have exact python semantics."""

    def __init__(self):
        self._counter = 0

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and 1 <= len(it.args) <= 3
                and not it.keywords
                and not any(isinstance(a, ast.Starred) for a in it.args)):
            return node
        self._counter += 1
        k = self._counter
        i, stop, step = (f"_jst_i_{k}", f"_jst_stop_{k}", f"_jst_step_{k}")
        tgt = node.target.id
        prologue = [
            ast.Assign(
                targets=[ast.Tuple(elts=[_store(i), _store(stop),
                                         _store(step)], ctx=ast.Store())],
                value=_call("__jst_range3__", *it.args)),
            # bind the loop target before the while so it can be a
            # lax.while_loop carry (divergence from python: after a
            # ZERO-iteration loop the target holds the start value
            # instead of being unbound — the reference's loop transform
            # makes the same trade)
            _assign(tgt, _load(i)),
            _assign(i, ast.BinOp(left=_load(i), op=ast.Sub(),
                                 right=_load(step))),
        ]
        # the counter advances at the TOP of the body (i starts at
        # start-step, the test looks one step ahead): a `continue` lowered
        # by _BreakContinue guards every statement AFTER the flag set, and
        # a trailing increment under that guard would never run again —
        # the loop would spin forever
        body = ([_assign(i, ast.BinOp(left=_load(i), op=ast.Add(),
                                      right=_load(step))),
                 _assign(tgt, _load(i))] + list(node.body))
        loop = ast.While(
            test=_call("__jst_range_cont__",
                       ast.BinOp(left=_load(i), op=ast.Add(),
                                 right=_load(step)),
                       _load(stop), _load(step)),
            body=body, orelse=[])
        return prologue + [loop]


# ---------------------------------------------------------------------------
# Pass 2: return inside control flow -> flag + value
# (return_transformer.py)
# ---------------------------------------------------------------------------

_RET_FLAG = "_jst_ret_flag"
_RET_VAL = "_jst_ret_val"


class _Fallback(Exception):
    """Shape the transform cannot express; degrade to the original fn."""


def _transform_returns(fn_def) -> bool:
    """Rewrite returns nested inside If/While into `_jst_ret_flag/_val`
    assignments with guard chains; returns True if anything changed.
    Raises _Fallback for shapes we refuse (yield, return in try or in a
    python for)."""

    def ret_inside_cf(stmts) -> bool:
        for st in stmts:
            for sub in ast.walk(st):
                if isinstance(sub, _FN_SCOPES):
                    continue
                if isinstance(sub, (ast.If, ast.While, ast.For, ast.Try)):
                    if _contains(sub.body + getattr(sub, "orelse", [])
                                 + getattr(sub, "finalbody", []),
                                 (ast.Return,)):
                        return True
        return False

    if not ret_inside_cf(fn_def.body):
        return False
    # refuse shapes with no sound rewrite
    for st in fn_def.body:
        for sub in ast.walk(st):
            if isinstance(sub, _FN_SCOPES):
                continue
            if isinstance(sub, (ast.Try,)) and \
                    _contains([sub], (ast.Return,)):
                raise _Fallback("return inside try")
            if isinstance(sub, ast.For) and \
                    _contains(sub.body, (ast.Return,)):
                raise _Fallback("return inside python for")

    def rew(stmts) -> Tuple[List, bool]:
        out: List = []
        for idx, st in enumerate(stmts):
            if isinstance(st, ast.Return):
                out.append(_assign(_RET_VAL,
                                   st.value or _const(None)))
                out.append(_assign(_RET_FLAG, _const(True)))
                return out, True           # rest is unreachable
            if isinstance(st, (ast.If, ast.While)) and _contains(
                    [st], (ast.Return,)):
                if isinstance(st, ast.If):
                    nb, _ = rew(st.body)
                    ne, _ = rew(st.orelse)
                    st2 = ast.If(test=st.test, body=nb or [ast.Pass()],
                                 orelse=ne)
                else:
                    nb, _ = rew(st.body)
                    st2 = ast.While(
                        test=_call("__jst_and__",
                                   _call("__jst_not__", _load(_RET_FLAG)),
                                   st.test),
                        body=nb, orelse=st.orelse)
                out.append(st2)
                rest, _ = rew(stmts[idx + 1:])
                if rest:
                    out.append(_guard_if(_load(_RET_FLAG), rest))
                return out, True
            out.append(st)
        return out, False

    new_body, _ = rew(fn_def.body)
    fn_def.body = ([_assign(_RET_FLAG, _const(False)),
                    _assign(_RET_VAL, _const(None))]
                   + new_body
                   + [ast.Return(value=_load(_RET_VAL))])
    return True


# ---------------------------------------------------------------------------
# Pass 3: break/continue -> carried flags (break_continue_transformer.py)
# ---------------------------------------------------------------------------


class _BreakContinue(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def visit_While(self, node: ast.While):
        self.generic_visit(node)          # inner loops first
        if node.orelse:
            return node
        stop_at = (ast.While, ast.For)
        has_brk = _contains(node.body, (ast.Break,), stop=stop_at)
        has_cnt = _contains(node.body, (ast.Continue,), stop=stop_at)
        if not (has_brk or has_cnt):
            return node
        self._counter += 1
        k = self._counter
        brk = f"_jst_brk_{k}"
        cnt = f"_jst_cnt_{k}"

        def flags_or():
            e = None
            for nm in ([brk] if has_brk else []) + ([cnt] if has_cnt
                                                   else []):
                e = _load(nm) if e is None else _call("__jst_or__", e,
                                                      _load(nm))
            return e

        def rew(stmts) -> Tuple[List, bool]:
            out: List = []
            for idx, st in enumerate(stmts):
                if isinstance(st, ast.Break):
                    out.append(_assign(brk, _const(True)))
                    return out, True
                if isinstance(st, ast.Continue):
                    out.append(_assign(cnt, _const(True)))
                    return out, True
                if isinstance(st, ast.If) and _contains(
                        [st], (ast.Break, ast.Continue), stop=stop_at):
                    nb, _ = rew(st.body)
                    ne, _ = rew(st.orelse)
                    out.append(ast.If(test=st.test,
                                      body=nb or [ast.Pass()], orelse=ne))
                    rest, _ = rew(stmts[idx + 1:])
                    if rest:
                        out.append(_guard_if(flags_or(), rest))
                    return out, True
                out.append(st)
            return out, False

        body, _ = rew(node.body)
        if has_cnt:
            body = [_assign(cnt, _const(False))] + body
        test = node.test
        if has_brk:
            test = _call("__jst_and__", _call("__jst_not__", _load(brk)),
                         test)
        prologue = [_assign(brk, _const(False))] if has_brk else []
        return prologue + [ast.While(test=test, body=body, orelse=[])]


# ---------------------------------------------------------------------------
# Pass 4: functionalize if/while (ifelse_transformer / loop_transformer)
# ---------------------------------------------------------------------------


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _next(self, kind):
        self._counter += 1
        return f"__jst_{kind}_{self._counter}"

    # -- if ---------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            return node
        body_names = {m for m in _assigned_names(node.body)
                      if not m.startswith("__jst_")}
        else_names = {m for m in _assigned_names(node.orelse)
                      if not m.startswith("__jst_")}
        if _has_scope_decl(node.body) or _has_scope_decl(node.orelse) \
                or _has_nonname_binding(node.body) \
                or _has_nonname_binding(node.orelse):
            return node        # global/nonlocal/import/def in a branch
        # mod is the UNION: a name assigned in one branch only is carried
        # through the other unchanged (its incoming value is the branch
        # result) — names with no prior binding must be assigned by BOTH
        # branches to functionalize under trace (checked at runtime via
        # `both`)
        mod = sorted(body_names | else_names)
        both = tuple(sorted(body_names & else_names))
        name_t = self._next("true")
        name_f = self._next("false")
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=m)
                                                   for m in mod],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(elts=[_load(m) for m in mod],
                                         ctx=ast.Load()))
        tbody = (node.body or [ast.Pass()]) + [ret]
        fbody = (node.orelse or [ast.Pass()]) + [ret]
        fn_t = _fn_def(name_t, args, tbody)
        fn_f = _fn_def(name_f, args, fbody)
        call = ast.Call(func=_load("__jst_if__"),
                        args=[node.test, _load(name_t), _load(name_f),
                              ast.Tuple(elts=[_load(m) for m in mod],
                                        ctx=ast.Load()),
                              _const(tuple(mod)), _const(both)],
                        keywords=[])
        if mod:
            assign = ast.Assign(
                targets=[ast.Tuple(elts=[_store(m) for m in mod],
                                   ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [_undef_guard(m) for m in mod] + [fn_t, fn_f, assign]

    # -- while ------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse or _has_flow_escape(node.body):
            return node
        mod = sorted(m for m in _assigned_names(node.body)
                     if not m.startswith("__jst_"))
        if not mod or _has_scope_decl(node.body) \
                or _has_nonname_binding(node.body) \
                or any(isinstance(sub, ast.NamedExpr)
                       for sub in ast.walk(node.test)):
            # a walrus in the condition binds a name the body reads; the
            # binding would become local to the generated cond function
            return node
        name_c = self._next("cond")
        name_b = self._next("body")
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=m)
                                                   for m in mod],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        fn_c = _fn_def(name_c, args, [ast.Return(value=node.test)])
        fn_b = _fn_def(name_b, args,
                       list(node.body) + [ast.Return(value=ast.Tuple(
                           elts=[_load(m) for m in mod], ctx=ast.Load()))])
        call = ast.Call(func=_load("__jst_while__"),
                        args=[_load(name_c), _load(name_b),
                              ast.Tuple(elts=[_load(m) for m in mod],
                                        ctx=ast.Load()),
                              _const(tuple(mod))],
                        keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_store(m) for m in mod],
                               ctx=ast.Store())],
            value=call)
        return [_undef_guard(m) for m in mod] + [fn_c, fn_b, assign]


# ---------------------------------------------------------------------------
# runtime dispatch helpers
# ---------------------------------------------------------------------------


def _raw(x):
    from ..core.tensor import Tensor
    return x._data if isinstance(x, Tensor) else x


def __jst_if__(test, true_fn, false_fn, vals, names, both=()):
    from ..core.tensor import _is_tracer
    raw = _raw(test)
    # ONLY tracers take the functional branch: an eager concrete Tensor
    # keeps exact python semantics (one branch runs, side effects intact)
    if _is_tracer(raw):
        from ..static import nn as snn
        # names with no prior binding carry the sentinel; when both
        # branches assign them (they never read the incoming value) hand
        # the tracer a benign zero — otherwise the structures of the two
        # branch results cannot match
        clean = []
        for n, v in zip(names, vals):
            if v is _UNDEF:
                if n not in both:
                    raise NameError(
                        f"variable {n!r} is assigned in only one branch "
                        "of a tensor-dependent if and has no value "
                        "before it; initialize it before the if so both "
                        "branches produce the same structure")
                clean.append(0)
            else:
                clean.append(v)
        try:
            return snn.cond(test, true_fn, false_fn, *clean)
        except TypeError as e:
            # jax has spelled the branch-structure mismatch two ways:
            # "pytree structure" (newer) and "same type structure ...
            # PyTreeDef" (0.4.x) — both mean the same recoverable shape
            msg = str(e)
            if "pytree structure" not in msg and \
                    "type structure" not in msg:
                raise
            # Structure mismatch — typically a return-transform carry
            # whose initial value is None on one side and a tensor on the
            # other. Lower as inline-both-branches + elementwise select
            # (what XLA does for cheap conds anyway); None promotes to
            # zeros, which every LIVE path overwrites under its flag
            # guard before the final return.
            return _inline_select(test, true_fn, false_fn, clean, e)
    return true_fn(*vals) if test else false_fn(*vals)


def _inline_select(test, true_fn, false_fn, clean, orig_err):
    from ..core.tensor import Tensor, apply
    import jax.numpy as jnp
    outs_t = true_fn(*clean)
    outs_f = false_fn(*clean)
    if not isinstance(outs_t, tuple):
        outs_t, outs_f = (outs_t,), (outs_f,)
    if len(outs_t) != len(outs_f):
        raise TypeError(
            "tensor-dependent `if`: the two paths produce a different "
            f"number of values ({len(outs_t)} vs {len(outs_f)}); use "
            "paddle.static.nn.cond with matching branch structures.\n\n"
            "original error: " + str(orig_err))

    def is_val(x):
        return isinstance(x, (Tensor, bool, int, float, complex)) \
            or hasattr(x, "dtype")

    out = []
    for t, f in zip(outs_t, outs_f):
        if t is None and f is None:
            out.append(None)
            continue
        if not ((is_val(t) or t is None) and (is_val(f) or f is None)):
            raise TypeError(
                "tensor-dependent `if`: the two paths produce "
                f"incompatible values ({type(t).__name__} vs "
                f"{type(f).__name__}); use paddle.static.nn.cond with "
                "matching branch structures, or jnp.where for "
                "elementwise selects.\n\noriginal error: "
                + str(orig_err))

        def sel(p, a, b):
            if a is None:
                a = jnp.zeros_like(b)
            if b is None:
                b = jnp.zeros_like(a)
            return jnp.where(p, a, b)

        args = [x for x in (test, t, f) if x is not None]
        if t is None:
            out.append(apply(lambda p, b: sel(p, None, b), *args,
                             name="jst_select"))
        elif f is None:
            out.append(apply(lambda p, a: sel(p, a, None), *args,
                             name="jst_select"))
        else:
            out.append(apply(sel, *args, name="jst_select"))
    return tuple(out)


def __jst_while__(cond_fn, body_fn, vals, names):
    from ..core.tensor import _is_tracer
    vals = tuple(vals)
    while True:
        first = cond_fn(*vals)
        raw = _raw(first)
        if _is_tracer(raw):
            # the condition is traced — either from the first evaluation
            # or because a break/return flag became traced mid-loop (set
            # inside a lax.cond); hand the CURRENT carries to
            # lax.while_loop. Names with no binding before the loop
            # (_UNDEF) are loop-LOCAL temporaries, not carries: the body
            # receives the sentinel and must write before reading (a
            # read raises the sentinel's clear NameError); their
            # post-loop value stays unbound, as in python after a
            # zero-iteration loop.
            live = [i for i, v in enumerate(vals) if v is not _UNDEF]
            from ..static import nn as snn
            if len(live) == len(vals):
                out = snn.while_loop(cond_fn, body_fn, list(vals))
                return tuple(out) if isinstance(out, (list, tuple)) \
                    else (out,)

            def full(live_vals):
                it = iter(live_vals)
                return [next(it) if i in set(live) else _UNDEF
                        for i in range(len(vals))]

            def cond2(*lv):
                return cond_fn(*full(lv))

            def body2(*lv):
                out = body_fn(*full(lv))
                return tuple(out[i] for i in live)

            out = snn.while_loop(cond2, body2,
                                 [vals[i] for i in live])
            out = list(out) if isinstance(out, (list, tuple)) else [out]
            it = iter(out)
            return tuple(next(it) if i in set(live) else _UNDEF
                         for i in range(len(vals)))
        if not bool(first):
            # after a zero-iteration loop, inside-only names stay the
            # _Unbound sentinel: carrying/reassigning it is fine, USING
            # it raises a clear NameError (python's unbound-local
            # contract)
            return vals
        vals = tuple(body_fn(*vals))


def __jst_not__(x):
    from ..core.tensor import Tensor, apply
    if isinstance(x, Tensor) or hasattr(x, "dtype"):
        import jax.numpy as jnp
        return apply(jnp.logical_not, x, name="jst_not")
    return not x


def _jst_bool2(op_name, jnp_op, a, b):
    from ..core.tensor import Tensor, apply
    if isinstance(a, Tensor) or isinstance(b, Tensor) \
            or hasattr(a, "dtype") or hasattr(b, "dtype"):
        import jax.numpy as jnp
        return apply(lambda x, y: jnp_op(jnp.asarray(x, bool),
                                         jnp.asarray(y, bool)),
                     a, b, name=op_name)
    return None


def __jst_and__(a, b):
    import jax.numpy as jnp
    out = _jst_bool2("jst_and", jnp.logical_and, a, b)
    # NOTE: tensor operands evaluate both sides (no short circuit) — the
    # lax lowering cannot skip either anyway
    return (a and b) if out is None else out


def __jst_or__(a, b):
    import jax.numpy as jnp
    out = _jst_bool2("jst_or", jnp.logical_or, a, b)
    return (a or b) if out is None else out


def __jst_range3__(*args):
    """Normalize range endpoints WITHOUT constructing range() — tensor
    endpoints stay tensors and drive a lax.while_loop."""
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args


def __jst_range_cont__(i, stop, step):
    from ..core.tensor import Tensor, apply
    if isinstance(i, Tensor) or isinstance(stop, Tensor) \
            or isinstance(step, Tensor) or hasattr(i, "dtype") \
            or hasattr(stop, "dtype") or hasattr(step, "dtype"):
        import jax.numpy as jnp

        def f(iv, sv, st):
            return jnp.where(st > 0, iv < sv, iv > sv)

        return apply(f, i, stop, step, name="jst_range_cont")
    return i < stop if step > 0 else i > stop


# ---------------------------------------------------------------------------


def ast_transform(func: Callable) -> Optional[Callable]:
    """Return a control-flow-functionalized version of `func`, or None if
    the function cannot be transformed (no source, closures, lambdas)."""
    try:
        if func.__closure__:
            return None                  # cell vars can't be recompiled
        src = textwrap.dedent(inspect.getsource(func))
    except (OSError, TypeError, AttributeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fn_def = tree.body[0]
    if not isinstance(fn_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    if _contains(fn_def.body, (ast.Yield, ast.YieldFrom)):
        return None                      # generators keep python semantics
    fn_def.decorator_list = []           # avoid re-applying @to_static
    try:
        tree = _ForToWhile().visit(tree)
        _transform_returns(fn_def)
        tree = _BreakContinue().visit(tree)
        new_tree = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(new_tree)
        # execute against the function's LIVE module globals so late-bound
        # helpers, recursion and mutated module state keep working; the
        # dispatch helpers ride prefixed names that cannot clash
        globs = func.__globals__
        globs.setdefault("__jst_if__", __jst_if__)
        globs.setdefault("__jst_while__", __jst_while__)
        globs.setdefault("__jst_undef__", _UNDEF)
        globs.setdefault("__jst_not__", __jst_not__)
        globs.setdefault("__jst_and__", __jst_and__)
        globs.setdefault("__jst_or__", __jst_or__)
        globs.setdefault("__jst_range3__", __jst_range3__)
        globs.setdefault("__jst_range_cont__", __jst_range_cont__)
        code = compile(new_tree,
                       filename=f"<dy2static {func.__qualname__}>",
                       mode="exec")
        ns: dict = {}
        exec(code, globs, ns)
        new_fn = ns[fn_def.name]
    except Exception:
        return None                      # degrade to the original function
    new_fn.__defaults__ = func.__defaults__
    new_fn.__kwdefaults__ = func.__kwdefaults__
    return functools.wraps(func)(new_fn)


def convert_to_static(func: Callable) -> Callable:
    """Transform, falling back to the original on any limitation."""
    out = ast_transform(func)
    return out if out is not None else func
