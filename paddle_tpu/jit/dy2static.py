"""Dygraph-to-static AST transform: python `if`/`while` over tensors.

reference parity: the dygraph_to_static AST translator
(reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:768, ifelse_transformer.py IfElseTransformer,
loop_transformer.py LoopTransformer) which rewrites python control flow
into conditional_block/while ops.

TPU-native redesign: the transform functionalizes each `if`/`while`
into a call to a dispatch helper — `__jst_if__` / `__jst_while__` —
passing the variables either branch assigns as explicit arguments
(parameters shadow the outer names, so branch bodies run unchanged).
At RUNTIME the helper checks the condition's type: a concrete python
bool takes the normal python path (zero overhead, exact semantics);
a traced/eager Tensor routes to `static.nn.cond` / `while_loop`
(lax.cond / lax.while_loop), which is the XLA-compilable form.

Deliberately restricted (falls back to the untransformed statement,
where tracing's guided ConcretizationTypeError explains the options):
- branches containing return / break / continue / yield
- variables created in only one branch and never defined before the if
  (both branches must produce every output)
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, Optional

__all__ = ["ast_transform", "convert_to_static"]

class _Unbound:
    """Placeholder for a name with no binding before the control flow.
    Harmless to carry and reassign; USING it raises a clear NameError
    (mirroring python's unbound-local behavior)."""

    def __repr__(self):
        return "<unbound dy2static variable>"

    def _raise(self, *a, **k):
        raise NameError(
            "variable was only assigned inside control flow that did not "
            "execute; initialize it before the if/while")

    __bool__ = __getattr__ = __call__ = __add__ = __radd__ = __sub__ = \
        __mul__ = __iter__ = __len__ = __float__ = __int__ = _raise


# single sentinel instance shared by all transformed functions
_UNDEF = _Unbound()


def _assigned_names(nodes) -> set:
    out = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
            elif isinstance(sub, ast.AugAssign) and \
                    isinstance(sub.target, ast.Name):
                out.add(sub.target.id)
    return out


def _has_scope_decl(nodes) -> bool:
    return any(isinstance(sub, (ast.Global, ast.Nonlocal))
               for n in nodes for sub in ast.walk(n))


def _has_nonname_binding(nodes) -> bool:
    """import / def / class statements bind names invisibly to the
    Name-store scan; functionalizing such a branch would trap the binding
    in the generated function's locals."""
    return any(isinstance(sub, (ast.Import, ast.ImportFrom,
                                ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef))
               for n in nodes for sub in ast.walk(n))


def _has_flow_escape(nodes) -> bool:
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                return True
            if isinstance(sub, (ast.Break, ast.Continue)):
                # only count break/continue that would escape THIS block
                # (ones inside a nested loop are fine) — conservative:
                # treat any as escaping
                return True
    return False


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _fn_def(name, args, body):
    fd = ast.FunctionDef(name=name, args=args, body=body,
                         decorator_list=[], returns=None)
    fd.type_params = []          # py3.12+ field
    return fd


def _undef_guard(name):
    """`try: name\nexcept NameError: name = __jst_undef__` — lets
    `if c: y = a else: y = b` work when y has no prior binding."""
    return ast.Try(
        body=[ast.Expr(value=_load(name))],
        handlers=[ast.ExceptHandler(
            type=_load("NameError"), name=None,
            body=[ast.Assign(targets=[_store(name)],
                             value=_load("__jst_undef__"))])],
        orelse=[], finalbody=[])


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _next(self, kind):
        self._counter += 1
        return f"__jst_{kind}_{self._counter}"

    # -- if ---------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            return node
        body_names = {m for m in _assigned_names(node.body)
                      if not m.startswith("__jst_")}
        else_names = {m for m in _assigned_names(node.orelse)
                      if not m.startswith("__jst_")}
        if body_names != else_names:
            # a name produced by only one branch cannot be functionalized
            # (lax.cond branches must return identical structures); leave
            # the python `if` intact — eager semantics are exact, and
            # tracing raises the guided concretization error
            return node
        if _has_scope_decl(node.body) or _has_scope_decl(node.orelse) \
                or _has_nonname_binding(node.body) \
                or _has_nonname_binding(node.orelse):
            return node        # global/nonlocal/import/def in a branch
        mod = sorted(body_names)
        name_t = self._next("true")
        name_f = self._next("false")
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=m)
                                                   for m in mod],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(elts=[_load(m) for m in mod],
                                         ctx=ast.Load()))
        tbody = (node.body or [ast.Pass()]) + [ret]
        fbody = (node.orelse or [ast.Pass()]) + [ret]
        fn_t = _fn_def(name_t, args, tbody)
        fn_f = _fn_def(name_f, args, fbody)
        call = ast.Call(func=_load("__jst_if__"),
                        args=[node.test, _load(name_t), _load(name_f),
                              ast.Tuple(elts=[_load(m) for m in mod],
                                        ctx=ast.Load()),
                              ast.Constant(value=tuple(mod))],
                        keywords=[])
        if mod:
            assign = ast.Assign(
                targets=[ast.Tuple(elts=[_store(m) for m in mod],
                                   ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [_undef_guard(m) for m in mod] + [fn_t, fn_f, assign]

    # -- while ------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse or _has_flow_escape(node.body):
            return node
        mod = sorted(m for m in _assigned_names(node.body)
                     if not m.startswith("__jst_"))
        if not mod or _has_scope_decl(node.body) \
                or _has_nonname_binding(node.body) \
                or any(isinstance(sub, ast.NamedExpr)
                       for sub in ast.walk(node.test)):
            # a walrus in the condition binds a name the body reads; the
            # binding would become local to the generated cond function
            return node
        name_c = self._next("cond")
        name_b = self._next("body")
        args = ast.arguments(posonlyargs=[], args=[ast.arg(arg=m)
                                                   for m in mod],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        fn_c = _fn_def(name_c, args, [ast.Return(value=node.test)])
        fn_b = _fn_def(name_b, args,
                       list(node.body) + [ast.Return(value=ast.Tuple(
                           elts=[_load(m) for m in mod], ctx=ast.Load()))])
        call = ast.Call(func=_load("__jst_while__"),
                        args=[_load(name_c), _load(name_b),
                              ast.Tuple(elts=[_load(m) for m in mod],
                                        ctx=ast.Load()),
                              ast.Constant(value=tuple(mod))],
                        keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_store(m) for m in mod],
                               ctx=ast.Store())],
            value=call)
        return [_undef_guard(m) for m in mod] + [fn_c, fn_b, assign]


def __jst_if__(test, true_fn, false_fn, vals, names):
    from ..core.tensor import Tensor, _is_tracer
    raw = test._data if isinstance(test, Tensor) else test
    # ONLY tracers take the functional branch: an eager concrete Tensor
    # keeps exact python semantics (one branch runs, side effects intact)
    if _is_tracer(raw):
        from ..static import nn as snn
        # names with no prior binding carry the sentinel; both branches
        # assign them (they never read the incoming value), so hand the
        # tracer a benign zero instead of a non-JAX object
        vals = tuple(0 if v is _UNDEF else v for v in vals)
        return snn.cond(test, true_fn, false_fn, *vals)
    return true_fn(*vals) if test else false_fn(*vals)


def __jst_while__(cond_fn, body_fn, vals, names):
    from ..core.tensor import Tensor, _is_tracer
    undef = [n for n, v in zip(names, vals) if v is _UNDEF]
    first = cond_fn(*vals)
    raw = first._data if isinstance(first, Tensor) else first
    if _is_tracer(raw):
        if undef:
            raise NameError(
                f"loop variable(s) {undef} are assigned inside a "
                "tensor-dependent while but have no value before it; "
                "lax.while_loop carries need an initial binding — "
                "initialize them before the loop")
        from ..static import nn as snn
        out = snn.while_loop(cond_fn, body_fn, list(vals))
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)
    while bool(first):
        vals = body_fn(*vals)
        first = cond_fn(*vals)
    # after a zero-iteration loop, inside-only names stay the _Unbound
    # sentinel: carrying/reassigning it is fine, USING it raises a clear
    # NameError (python's unbound-local contract)
    return tuple(vals)


def ast_transform(func: Callable) -> Optional[Callable]:
    """Return a control-flow-functionalized version of `func`, or None if
    the function cannot be transformed (no source, closures, lambdas)."""
    try:
        if func.__closure__:
            return None                  # cell vars can't be recompiled
        src = textwrap.dedent(inspect.getsource(func))
    except (OSError, TypeError, AttributeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fn_def = tree.body[0]
    if not isinstance(fn_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fn_def.decorator_list = []           # avoid re-applying @to_static
    try:
        new_tree = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(new_tree)
        # execute against the function's LIVE module globals so late-bound
        # helpers, recursion and mutated module state keep working; the
        # dispatch helpers ride prefixed names that cannot clash
        globs = func.__globals__
        globs.setdefault("__jst_if__", __jst_if__)
        globs.setdefault("__jst_while__", __jst_while__)
        globs.setdefault("__jst_undef__", _UNDEF)
        code = compile(new_tree,
                       filename=f"<dy2static {func.__qualname__}>",
                       mode="exec")
        ns: dict = {}
        exec(code, globs, ns)
        new_fn = ns[fn_def.name]
    except Exception:
        return None                      # degrade to the original function
    new_fn.__defaults__ = func.__defaults__
    new_fn.__kwdefaults__ = func.__kwdefaults__
    return functools.wraps(func)(new_fn)


def convert_to_static(func: Callable) -> Callable:
    """Transform, falling back to the original on any limitation."""
    out = ast_transform(func)
    return out if out is not None else func
