"""paddle_tpu.jit — trace-and-compile (reference: python/paddle/jit/).

`to_static` compiles a Layer (or function over Tensors) into cached XLA
executables per input signature — the TPU-native analogue of the reference's
ProgramTranslator, with tracing instead of AST rewriting. `save`/`load`
export StableHLO in place of the reference's inference ProgramDesc.
"""

from .functional import bind, functional_call, param_arrays, unwrap, wrap  # noqa: F401
from .to_static import StaticFunction, save, load, to_static, TrainStep  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
