from .functional import grad, hessian, jacobian, jvp, vjp  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from ..core.tensor import backward, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
