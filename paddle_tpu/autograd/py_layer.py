"""PyLayer: custom forward/backward (reference: python/paddle/autograd/py_layer.py).

The tape integration is direct: PyLayer.apply runs the user's forward with a
context, then records a tape node whose vjp calls the user's backward."""

from __future__ import annotations

import weakref
from typing import Any

import jax
import jax.numpy as jnp

from ..core.tensor import TapeNode, Tensor, is_grad_enabled


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        outputs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outputs, (tuple, list))
        out_list = list(outputs) if multi else [outputs]

        tensor_inputs = [a for a in args if isinstance(a, Tensor)
                         and not a.stop_gradient]
        if not is_grad_enabled() or not tensor_inputs:
            return outputs

        def vjp_fn(cots):
            cot_list = cots if isinstance(cots, tuple) else (cots,)
            cot_tensors = [Tensor(c) for c in cot_list]
            grads = cls.backward(ctx, *cot_tensors)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            out = []
            gi = 0
            for t in tensor_inputs:
                g = grads[gi] if gi < len(grads) else None
                gi += 1
                if g is None:
                    out.append(jnp.zeros(tuple(t.shape), t.dtype))
                else:
                    out.append(g._data if isinstance(g, Tensor) else g)
            return tuple(out)

        out_avals = [jax.ShapeDtypeStruct(tuple(t.shape), t.dtype) for t in out_list]
        node = TapeNode(vjp_fn, tensor_inputs, out_avals, name=cls.__name__)
        for i, t in enumerate(out_list):
            t._node = node
            t._out_idx = i
            t.stop_gradient = False
            node.out_refs[i] = weakref.ref(t)
        return outputs
