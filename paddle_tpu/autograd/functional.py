"""paddle.grad analogue (reference: imperative PartialGradEngine,
paddle/fluid/imperative/partial_grad_engine.cc).

Runs a partial backward over the eager tape without touching ``.grad`` of
unrelated leaves, optionally building a differentiable graph for
double-grad (create_graph)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax.numpy as jnp

from ..core.tensor import Tensor, backward


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    # Save current .grad of inputs, run backward, read, restore.
    saved = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None

    retain = retain_graph if retain_graph is not None else create_graph
    for i, out in enumerate(outputs):
        gt = grad_outputs[i] if grad_outputs is not None else None
        backward(out, grad_tensor=gt, retain_graph=bool(retain))

    results: List[Optional[Tensor]] = []
    for t, old in zip(inputs, saved):
        g = t.grad
        if g is None and not allow_unused:
            g = Tensor(jnp.zeros(tuple(t.shape), t.dtype))
        results.append(g)
        t.grad = old
    return results


# ---------------------------------------------------------------------------
# Functional transforms (reference: python/paddle/autograd/functional.py —
# vjp/jvp/jacobian/hessian over executed functions).
# TPU-native: these lower straight onto jax's transforms (jacrev/jacfwd /
# jax.vjp/jvp) — the function is re-run under tracing with the leaf
# tensors as pure inputs, so the result is itself jit-compatible.
# ---------------------------------------------------------------------------


def _pure(func):
    """Wrap a Tensor-world callable as a pure array function."""
    def fn(*arrays):
        from ..core.tensor import no_grad
        with no_grad():
            out = func(*[Tensor(a) for a in arrays])
        if isinstance(out, (list, tuple)):
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in out)
        return out._data if isinstance(out, Tensor) else out
    return fn


def _raw_list(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [x._data if isinstance(x, Tensor) else jnp.asarray(x)
            for x in xs]


def _wrap_tree(tree):
    import jax
    return jax.tree_util.tree_map(Tensor, tree)


def vjp(func, xs, v=None):
    """(outputs, vjp_result) (reference: autograd/functional.py vjp)."""
    import jax
    raw = _raw_list(xs)
    single_input = not isinstance(xs, (list, tuple))
    out, vjp_fn = jax.vjp(_pure(func), *raw)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = tuple(_raw_list(v)) if isinstance(out, tuple) else \
            _raw_list([v])[0] if not isinstance(v, (list, tuple)) else \
            _raw_list(v)[0]
    grads = [Tensor(g) for g in vjp_fn(cot)]
    outs = _wrap_tree(out)
    # mirror the INPUT structure (like jacobian): list in -> list out
    return outs, grads[0] if single_input else grads


def jvp(func, xs, v=None):
    """(outputs, jvp_result) — forward-mode (reference: functional.jvp)."""
    import jax
    raw = _raw_list(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in raw)
    else:
        tangents = tuple(_raw_list(v))
    out, tangent_out = jax.jvp(_pure(func), tuple(raw), tangents)
    return _wrap_tree(out), _wrap_tree(tangent_out)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """Jacobian of func at xs (reference: functional.jacobian).

    Single input -> Tensor [*out_shape, *in_shape]; multiple inputs ->
    tuple of Jacobians, one per input."""
    import jax
    raw = _raw_list(xs)
    single = not isinstance(xs, (list, tuple))
    jac = jax.jacrev(_pure(func), argnums=tuple(range(len(raw))))(*raw)
    jac = _wrap_tree(jac)
    if single:
        return jac[0] if isinstance(jac, (list, tuple)) else jac
    return jac


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Hessian of a scalar-output func (reference: functional.hessian)."""
    import jax
    raw = _raw_list(xs)
    single = not isinstance(xs, (list, tuple))

    def scalar(*arrays):
        out = _pure(func)(*arrays)
        out = out[0] if isinstance(out, tuple) else out
        if out.ndim != 0:
            raise ValueError("hessian needs a scalar-output function, got "
                             f"output shape {out.shape}")
        return out

    hess = jax.hessian(scalar, argnums=tuple(range(len(raw))))(*raw)
    hess = _wrap_tree(hess)
    if single:
        h = hess
        while isinstance(h, (list, tuple)):
            h = h[0]
        return h
    return hess
