"""paddle.grad analogue (reference: imperative PartialGradEngine,
paddle/fluid/imperative/partial_grad_engine.cc).

Runs a partial backward over the eager tape without touching ``.grad`` of
unrelated leaves, optionally building a differentiable graph for
double-grad (create_graph)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax.numpy as jnp

from ..core.tensor import Tensor, backward


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    # Save current .grad of inputs, run backward, read, restore.
    saved = [t.grad for t in inputs]
    for t in inputs:
        t.grad = None

    retain = retain_graph if retain_graph is not None else create_graph
    for i, out in enumerate(outputs):
        gt = grad_outputs[i] if grad_outputs is not None else None
        backward(out, grad_tensor=gt, retain_graph=bool(retain))

    results: List[Optional[Tensor]] = []
    for t, old in zip(inputs, saved):
        g = t.grad
        if g is None and not allow_unused:
            g = Tensor(jnp.zeros(tuple(t.shape), t.dtype))
        results.append(g)
        t.grad = old
    return results
