"""Model compression: post-training quantization + QAT (paddle slim).

reference parity: the slim stack — post-training quantization
(reference: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py: calibrate activation ranges, quantize
weights channel-wise), QAT program rewrite
(quantization_pass.py: fake_quantize_dequantize ops with moving-average
ranges), and the int8 inference path (MKLDNN/TensorRT int8 kernels).

TPU-native redesign: quantization is a LAYER-TREE rewrite, not a graph
pass — `QuantizedLinear` replaces `nn.Linear` in place:
 - int8 end to end (`quantize_weights` + `FLAGS_pallas_int8`, the
   default): per-output-channel int8 weights stay int8 THROUGH the gemm
   — the Pallas kernel (ops.pallas.quant_matmul) quantizes the
   activation stream per tensor (dynamic absmax, or the calibrated
   `act_scale`) and runs int8 x int8 -> int32 on the MXU's native int8
   path with a dequantize epilogue. Weight HBM traffic is 1/4 the f32
   bytes AND the MXU runs at int8 rate — the win that matters for
   memory-bound TPU decode.
 - kill switch (`FLAGS_pallas_int8` off, or shapes the kernel cannot
   tile): the pre-kernel XLA paths — weight-only mode dequantizes the
   int8 weights into the matmul's float operand (XLA fuses the
   dequant-multiply into the gemm prologue), static-activation mode
   runs an XLA int8 dot.
 - static int8 activations (`PostTrainingQuantization`): calibration
   runs record per-layer absmax; `run()` bakes activation scales.
 - QAT (`QAT.quantize`): fake-quant straight-through estimators around
   weights+activations; `convert` strips them back to a quantized deploy
   model. The per-channel weight observer lives in
   `nn.quant.PerChannelAbsMaxObserver` (one scale rule shared with the
   kernel; docs/PARITY.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..nn.layer import Layer
from ..nn.layers.common import Linear

__all__ = ["QuantizedLinear", "quantize_weights",
           "PostTrainingQuantization", "QAT", "fake_quant"]


def _channel_scales(w: np.ndarray, bits: int = 8) -> np.ndarray:
    """Per-output-channel symmetric scales for a [in, out] weight —
    delegates to the one observer rule (nn.quant.PerChannelAbsMaxObserver)
    so slim, QAT and the Pallas int8 kernel can never disagree on the
    quantization grid."""
    from ..nn.quant import PerChannelAbsMaxObserver
    return PerChannelAbsMaxObserver(quant_bits=bits, quant_axis=1).observe(w)


class QuantizedLinear(Layer):
    """Linear with int8 weights (+ optional static int8 activations).

    Weight-only mode: y = x @ (q * scale) + b — the dequant multiply is
    fused by XLA into the gemm's operand read (weights move through HBM
    at 1/4 the f32 bytes).
    Static-activation mode (act_scale set): both operands are quantized
    and the gemm runs int8 x int8 -> int32 on the MXU, rescaled once.
    """

    def __init__(self, weight_q: np.ndarray, scale: np.ndarray, bias,
                 act_scale: Optional[float] = None):
        super().__init__()
        self.register_buffer("weight_q", Tensor(jnp.asarray(weight_q,
                                                            jnp.int8)))
        self.register_buffer("scale", Tensor(jnp.asarray(scale,
                                                         jnp.float32)))
        self.bias = None
        if bias is not None:
            self.bias = self.create_parameter(tuple(np.asarray(
                bias._data if isinstance(bias, Tensor) else bias).shape),
                is_bias=True)
            self.bias._data = jnp.asarray(
                bias._data if isinstance(bias, Tensor) else bias)
        self.act_scale = act_scale

    @classmethod
    def from_linear(cls, lin: Linear, act_scale: Optional[float] = None):
        w = np.asarray(lin.weight._data, np.float32)
        scale = _channel_scales(w)
        q = np.clip(np.round(w / scale[None, :]), -127, 127).astype(np.int8)
        return cls(q, scale, lin.bias, act_scale=act_scale)

    def forward(self, x):
        act_scale = self.act_scale
        # kernel dispatch resolved OUTSIDE the traced fn so the path
        # choice is stable for any cached trace; kill switch
        # FLAGS_pallas_int8 -> the pre-kernel XLA paths below
        from ..ops import pallas as pallas_ops
        use_kernel = pallas_ops.kernel_enabled("int8_matmul")
        if use_kernel:
            # quant_matmul (and with it jax.experimental.pallas) loads
            # only on a live-kernel path — the fallback paths keep the
            # kernel layer's lazy-import contract
            from ..ops.pallas.quant_matmul import matmul_shapes_supported
            K, N = (int(s) for s in self.weight_q.shape)
            if not matmul_shapes_supported(K, N):
                pallas_ops.note_fallback("int8_matmul", "shape")
                use_kernel = False

        def _kernel(a, q, s, *b):
            # weights stay int8 through the gemm; act_scale None =
            # dynamic per-tensor quantization of the activation stream
            from ..ops.pallas.quant_matmul import int8_linear
            return int8_linear(a, q, s, bias=b[0] if b else None,
                               act_scale=act_scale)

        def _wo(a, q, s, *b):
            w = q.astype(a.dtype) * s.astype(a.dtype)
            y = jnp.matmul(a, w)
            return y + b[0] if b else y

        def _int8(a, q, s, *b):
            aq = jnp.clip(jnp.round(a.astype(jnp.float32) / act_scale),
                          -127, 127).astype(jnp.int8)
            y = jax.lax.dot_general(
                aq, q, (((a.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = y.astype(jnp.float32) * (act_scale * s)
            y = y.astype(a.dtype)
            return y + b[0] if b else y

        if use_kernel:
            fn = _kernel
        else:
            fn = _wo if act_scale is None else _int8
        args = [x, self.weight_q, self.scale] + (
            [self.bias] if self.bias is not None else [])
        return apply(fn, *args, name="quantized_linear")

    def extra_repr(self):
        mode = "int8-act" if self.act_scale is not None else "weight-only"
        return f"in={self.weight_q.shape[0]}, out={self.weight_q.shape[1]}" \
               f", {mode}"


def _replace_linears(model: Layer, make, min_params: int) -> int:
    """Swap eligible Linear sublayers via `make(linear, qual_name)`."""
    count = 0
    for name, sub in list(model.named_sublayers(include_self=True)):
        for child_name, child in list(sub._sub_layers.items()):
            if type(child) is Linear:
                w = child.weight
                if int(np.prod(w.shape)) < min_params:
                    continue
                replacement = make(child, f"{name}.{child_name}".strip("."))
                if replacement is not None:
                    sub._sub_layers[child_name] = replacement
                    count += 1
    return count


def quantize_weights(model: Layer, min_params: int = 4096) -> int:
    """Weight-only int8 PTQ in place; returns #layers quantized.

    reference: slim WeightQuantization (weight_quantize_type
    'channel_wise_abs_max')."""
    return _replace_linears(
        model, lambda lin, _: QuantizedLinear.from_linear(lin), min_params)


class PostTrainingQuantization:
    """Static (activation) PTQ with absmax calibration.

    reference: slim post_training_quantization.py — feed calibration
    batches, record per-input absmax per quantized layer, then emit the
    quantized model. Usage:

        ptq = PostTrainingQuantization(model)
        for batch in calib_loader: ptq.collect(batch)   # forward passes
        qmodel = ptq.run()
    """

    def __init__(self, model: Layer, min_params: int = 4096):
        self.model = model
        self.min_params = min_params
        self._ranges: Dict[int, float] = {}
        self._hooks = []
        for _, sub in model.named_sublayers(include_self=True):
            if type(sub) is Linear and \
                    int(np.prod(sub.weight.shape)) >= min_params:
                self._hooks.append(
                    sub.register_forward_pre_hook(self._observe(id(sub))))

    def _observe(self, key):
        def hook(layer, inputs):
            x = inputs[0]
            m = float(jnp.abs(x._data if isinstance(x, Tensor) else x)
                      .max())
            self._ranges[key] = max(self._ranges.get(key, 0.0), m)
            return None
        return hook

    def collect(self, *batch):
        from ..core.tensor import no_grad
        with no_grad():
            self.model(*[b if isinstance(b, Tensor) else Tensor(b)
                         for b in batch])

    def run(self) -> Layer:
        for h in self._hooks:
            h.remove()

        def make(lin, _):
            m = self._ranges.get(id(lin))
            if m is None or m == 0.0:
                return None                      # never observed: keep f32
            return QuantizedLinear.from_linear(lin, act_scale=m / 127.0)

        _replace_linears(self.model, make, self.min_params)
        return self.model


def fake_quant(x, bits: int = 8, name=None):
    """Quantize-dequantize with a straight-through gradient (QAT
    building block; reference: fake_quantize_dequantize_moving_average op).
    """
    qmax = 2.0 ** (bits - 1) - 1

    def _fq(a):
        s = jnp.maximum(jnp.max(jnp.abs(a)) / qmax, 1e-8)
        q = jnp.clip(jnp.round(a / s), -qmax, qmax) * s
        # straight-through: forward the quantized value, backprop identity
        return a + jax.lax.stop_gradient(q - a)

    return apply(_fq, x if isinstance(x, Tensor) else Tensor(x),
                 name=name or "fake_quant")


class _QATLinear(Layer):
    """Linear trained under fake-quantized weights + activations."""

    def __init__(self, lin: Linear, bits: int = 8):
        super().__init__()
        self.inner = lin
        self.bits = bits

    def forward(self, x):
        from ..nn import functional as F
        xq = fake_quant(x, self.bits, name="fake_quant_act")
        wq = fake_quant(self.inner.weight, self.bits, name="fake_quant_w")
        return F.linear(xq, wq, self.inner.bias)


class QAT:
    """Quantization-aware training (reference: slim QuantizationTransformPass
    / paddle.quantization QAT): `quantize` wraps layers with fake-quant,
    `convert` emits the deployable int8 model."""

    def __init__(self, bits: int = 8, min_params: int = 4096):
        self.bits = bits
        self.min_params = min_params

    def quantize(self, model: Layer) -> Layer:
        _replace_linears(model, lambda lin, _: _QATLinear(lin, self.bits),
                         self.min_params)
        return model

    def convert(self, model: Layer) -> Layer:
        """Strip fake-quant wrappers -> QuantizedLinear deploy form."""
        for _, sub in list(model.named_sublayers(include_self=True)):
            for child_name, child in list(sub._sub_layers.items()):
                if isinstance(child, _QATLinear):
                    sub._sub_layers[child_name] = \
                        QuantizedLinear.from_linear(child.inner)
        return model
