"""NaN/Inf watchdog: eager post-step numerics checks.

Reference analogue: FLAGS_check_nan_inf + nan_inf_utils_detail.cu — the
reference scans every op's outputs on device. The repo already has that
in-graph form (``FLAGS_check_nan_inf`` compiles per-gradient finite flags
INTO the train step, jit/to_static.py). This module is the complementary
*eager* watchdog: it runs OUTSIDE the compiled step, so XLA fusion and
the compiled program are untouched — zero cost until something trips,
then a post-mortem names the first offending parameter/gradient and the
step index.

Used by ``TrainStep(check_numerics=...)`` (which re-runs a grads-only
diagnosis pass at the pre-update parameters on a trip) and usable
directly on eager training loops via :func:`check_numerics` /
:class:`NaNWatchdog`.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional

__all__ = ["NonFiniteError", "all_finite", "nonfinite_entries",
           "first_nonfinite", "check_numerics", "NaNWatchdog"]


class NonFiniteError(RuntimeError):
    """Raised when the watchdog finds a NaN/Inf; carries the offender name
    and the step index for programmatic handling."""

    def __init__(self, message: str, offender: Optional[str] = None,
                 step: Optional[int] = None):
        super().__init__(message)
        self.offender = offender
        self.step = step


def _raw(v):
    return v._data if hasattr(v, "_data") else v


def _is_finite(arr) -> bool:
    import jax.numpy as jnp
    a = _raw(arr)
    if not hasattr(a, "dtype"):
        import math
        return math.isfinite(a)
    if not jnp.issubdtype(a.dtype, jnp.floating):
        return True
    return bool(jnp.isfinite(a).all())


def all_finite(tree: Dict[str, Any]) -> bool:
    """One fused device check over a name->array dict: True iff every
    float entry is finite. O(1) host readbacks (single stacked reduction),
    the fast pre-check before the per-name walk."""
    import jax
    import jax.numpy as jnp
    flags = [jnp.isfinite(_raw(v)).all() for v in tree.values()
             if hasattr(_raw(v), "dtype")
             and jnp.issubdtype(_raw(v).dtype, jnp.floating)]
    if not flags:
        return True
    return bool(jax.numpy.stack(flags).all())


def nonfinite_entries(tree: Dict[str, Any]) -> List[str]:
    """Names (sorted) of entries containing any NaN/Inf."""
    return [k for k in sorted(tree) if not _is_finite(tree[k])]


def first_nonfinite(tree: Dict[str, Any]) -> Optional[str]:
    """First (sorted-name) entry with a non-finite value, or None.

    Name order, not op order: eager post-step checks see the final pytree,
    not the op stream, so "first" is deterministic by name — enough to
    point at the offending parameter/gradient."""
    for k in sorted(tree):
        if not _is_finite(tree[k]):
            return k
    return None


def check_numerics(tree: Dict[str, Any], step: Optional[int] = None,
                   what: str = "tensor", action: str = "raise",
                   registry=None) -> Optional[str]:
    """Check a name->array dict; on a non-finite entry record a
    ``numerics_nonfinite_total{what=...}`` counter and raise
    :class:`NonFiniteError` (``action="raise"``) or warn
    (``action="warn"``). Returns the offender name (None when clean)."""
    if all_finite(tree):
        return None
    offender = first_nonfinite(tree)
    from .metrics import get_registry
    reg = registry if registry is not None else get_registry()
    reg.counter("numerics_nonfinite_total",
                "NaN/Inf watchdog trips by kind").inc(what=what)
    at = f" at step {step}" if step is not None else ""
    msg = (f"NaN/Inf detected{at}: first non-finite {what} is "
           f"{offender!r} (check_numerics watchdog; see "
           f"docs/OBSERVABILITY.md)")
    if action == "warn":
        warnings.warn(msg, RuntimeWarning, stacklevel=2)
        return offender
    raise NonFiniteError(msg, offender=offender, step=step)


class NaNWatchdog:
    """Stateful watchdog for eager training loops.

    ::

        dog = NaNWatchdog()               # or action="warn"
        for step, batch in enumerate(loader):
            loss = loss_fn(model(batch))
            loss.backward()
            dog.check_loss(loss, step)
            dog.check_grads(model, step, scaler=scaler)
            opt.step(); opt.clear_grad()

    AMP integration: when an ENABLED :class:`~paddle_tpu.amp.GradScaler`
    is passed, non-finite gradients are the scaler's to handle — it will
    flag them at ``unscale_`` (non-finiteness survives unscaling) and
    SKIP the optimizer step, which is dynamic loss scaling working as
    designed. The watchdog records the trip (labelled
    ``handled="amp_skip"``) but does not raise, regardless of whether
    ``unscale_`` has run yet this iteration.
    """

    def __init__(self, action: str = "raise", registry=None):
        self.action = action
        self._registry = registry
        self.trips = 0

    def _reg(self):
        from .metrics import get_registry
        return self._registry if self._registry is not None \
            else get_registry()

    def check_loss(self, loss, step: Optional[int] = None) -> Optional[str]:
        if _is_finite(loss):
            return None
        self.trips += 1
        return check_numerics({"loss": loss}, step=step, what="loss",
                              action=self.action, registry=self._reg())

    def check_grads(self, layer_or_grads, step: Optional[int] = None,
                    scaler=None) -> Optional[str]:
        """``layer_or_grads``: a Layer (uses ``p.grad`` of named params) or
        a name->array dict."""
        if hasattr(layer_or_grads, "named_parameters"):
            grads = {k: p.grad for k, p in layer_or_grads.named_parameters()
                     if p.grad is not None}
        else:
            grads = dict(layer_or_grads)
        if all_finite(grads):
            return None
        self.trips += 1
        offender = first_nonfinite(grads)
        if scaler is not None and scaler.is_enable():
            # non-finiteness is invariant under unscaling (inf/k == inf),
            # so an ENABLED scaler is guaranteed to flag these grads at
            # unscale_ and skip the step — whether or not unscale_ has
            # run yet this iteration. Count it, don't kill the run.
            self._reg().counter(
                "numerics_nonfinite_total",
                "NaN/Inf watchdog trips by kind").inc(
                    what="grad", handled="amp_skip")
            return offender
        return check_numerics(grads, step=step, what="grad",
                              action=self.action, registry=self._reg())
