"""Crash flight recorder: a bounded ring of recent step records, dumped
to JSON when a run dies.

Production training crashes at step 40k tell you nothing unless the
process wrote down what it was doing: this module keeps the last N step
records (step index, loss, wall/dispatch ms, per-step RNG seed), a
bounded event log (recompiles, eager collectives, watchdog trips), and
an environment fingerprint (jax/jaxlib versions, device kind, git sha,
active flags) in host memory — O(1) per step, no device sync — and
serializes the whole thing to ``flight_recorder_<pid>.json`` on:

- an **unhandled exception** (``install()`` chains ``sys.excepthook``);
- a **NaN-watchdog trip** (``TrainStep(check_numerics=...)`` calls
  :func:`trip_dump` before raising/warning);
- an explicit :meth:`FlightRecorder.dump` call.

Hard crashes (SIGSEGV, deadlock SIGABRT) can't run python code, so
``install()`` also wires :mod:`faulthandler` to a sidecar
``flight_recorder_<pid>.traceback`` file.

Recording is populated by ``TrainStep`` when ``FLAGS_monitor`` or
``FLAGS_flight_recorder`` is on (both off = zero recorder writes on the
hot path, same contract as the metrics registry). Render a dump with
``python tools/monitor_report.py --flight flight_recorder_<pid>.json``.

The fault-tolerance stack (docs/FAULT_TOLERANCE.md) records its
*recovery events* here so a post-mortem reads as one timeline: the
event names in :data:`RECOVERY_EVENTS` — ``checkpoint_commit`` (a
checkpoint became durable+visible), ``checkpoint_fallback`` (an
invalid/torn checkpoint was skipped at resume), ``collective_timeout``
(the eager-collective watchdog tripped), ``nonfinite_skip`` (an update
was rolled back under ``skip_nonfinite_budget``), ``preempted``
(SIGTERM honoured with a final commit), ``chaos`` (an injected fault
fired) — are rendered as a dedicated "Recovery timeline" section by
``monitor_report.py --flight``.
"""

from __future__ import annotations

import collections
import json
import math
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "get_flight_recorder", "set_flight_recorder",
           "enabled", "safe_record_event", "trip_dump", "load_dump",
           "RECOVERY_EVENTS", "register_dump_provider"]

_EVENT_CAPACITY = 128

# event names that make up a run's recovery timeline (emitters:
# distributed/checkpoint, distributed/collective, jit/to_static,
# testing/chaos, serving/engine); monitor_report.py --flight renders
# these separately
RECOVERY_EVENTS = ("checkpoint_commit", "checkpoint_fallback",
                   "collective_timeout", "nonfinite_skip", "preempted",
                   "trip", "chaos", "request_failed", "request_expired",
                   "request_cancelled", "request_drained", "request_shed",
                   "decode_watchdog", "overload", "drained",
                   "replica_migration", "health_spike")


# dump-time attachment hooks: other forensic subsystems (the structured
# tracer) register a provider so every dump — crash, watchdog trip,
# explicit — carries their in-flight state under the given key. Called
# only at dump time (never on the hot path) and best-effort: a raising
# provider is skipped, the dump must still land.
_DUMP_PROVIDERS: Dict[str, Any] = {}


def register_dump_provider(key: str, fn) -> None:
    """Attach ``fn()``'s return value under ``doc[key]`` in every
    future dump. Re-registering a key replaces the provider."""
    _DUMP_PROVIDERS[key] = fn


def _json_safe(v: Any) -> Any:
    """One scalar → something json.dumps(allow_nan=False) accepts.
    Device scalars are read back HERE (dump time), never on the hot
    path; non-finite floats become strings ('nan' is the whole point of
    some dumps)."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    try:
        f = float(v)
    except Exception:
        return repr(v)
    if math.isfinite(f):
        return f
    return repr(f)


def _json_safe_tree(v: Any) -> Any:
    """Recursive :func:`_json_safe` over dicts/lists — provider output
    is arbitrary nested structure."""
    if isinstance(v, dict):
        return {str(k): _json_safe_tree(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe_tree(x) for x in v]
    return _json_safe(v)


class FlightRecorder:
    """Bounded in-memory black box for one training process."""

    def __init__(self, capacity: Optional[int] = None,
                 dump_dir: Optional[str] = None):
        if capacity is None:
            try:
                from ..core.flags import get_flag
                capacity = int(get_flag("flight_recorder_capacity"))
            except Exception:
                capacity = 256
        self.capacity = max(1, int(capacity))
        self._dump_dir = dump_dir
        self._lock = threading.Lock()
        self._steps: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._events: collections.deque = collections.deque(
            maxlen=_EVENT_CAPACITY)
        self._fingerprint: Optional[Dict[str, Any]] = None
        self._installed = False
        self._prev_excepthook = None
        self._faulthandler_file = None
        self.record_count = 0          # mutation probe (tests pin the
        self.dump_count = 0            # monitor-off hot path writes none)

    # -- recording (hot path: dict build + deque append, no sync) ----------
    def record_step(self, step: int, loss: Any = None,
                    wall_ms: Optional[float] = None,
                    dispatch_ms: Optional[float] = None,
                    kind: str = "step", **extra) -> None:
        """O(1): ``loss`` may be a DEVICE scalar — it is held by
        reference and only read back at dump time."""
        rec = {"step": int(step), "kind": kind, "loss": loss,
               "wall_ms": wall_ms, "dispatch_ms": dispatch_ms,
               "ts": time.time()}
        try:
            from ..core.random import default_generator
            rec["seed"] = default_generator().initial_seed()
        except Exception:
            pass
        if extra:
            rec.update(extra)
        with self._lock:
            self._steps.append(rec)
            self.record_count += 1

    def record_event(self, event: str, **fields) -> None:
        """Recompiles, collective dispatches, watchdog trips — anything
        sparse enough to want exact records instead of counters."""
        rec = {"event": event, "ts": time.time()}
        rec.update(fields)
        with self._lock:
            self._events.append(rec)
            self.record_count += 1

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()
            self._events.clear()

    @property
    def steps(self) -> List[dict]:
        with self._lock:
            return list(self._steps)

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    # -- fingerprint -------------------------------------------------------
    def fingerprint(self) -> Dict[str, Any]:
        """Environment identity, computed once: enough to answer 'what
        exactly was this run' from the dump alone."""
        if self._fingerprint is not None:
            return self._fingerprint
        fp: Dict[str, Any] = {"pid": os.getpid(),
                              "argv": list(sys.argv),
                              "python": sys.version.split()[0]}
        try:
            import jax
            import jaxlib
            fp["jax_version"] = jax.__version__
            fp["jaxlib_version"] = getattr(jaxlib, "__version__", "?")
            devs = jax.devices()
            fp["backend"] = jax.default_backend()
            fp["device_kind"] = devs[0].device_kind if devs else "?"
            fp["device_count"] = len(devs)
        except Exception:
            pass
        try:
            from .. import version
            fp["paddle_tpu_version"] = version.full_version
        except Exception:
            pass
        fp["git_sha"] = self._git_sha()
        self._fingerprint = fp
        return fp

    @staticmethod
    def _git_sha() -> Optional[str]:
        import subprocess
        try:
            root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            out = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=root,
                capture_output=True, text=True, timeout=5)
            sha = out.stdout.strip()
            return sha or None
        except Exception:
            return None

    def _flags_snapshot(self) -> Dict[str, Any]:
        try:
            from ..core import flags as F
            return {name: _json_safe(F.get_flag(name))
                    for name in sorted(F._REGISTRY)}
        except Exception:
            return {}

    # -- dumping -----------------------------------------------------------
    def default_path(self, suffix: str = ".json") -> str:
        d = self._dump_dir
        if not d:
            try:
                from ..core.flags import get_flag
                d = get_flag("flight_recorder_dir")
            except Exception:
                d = ""
        d = d or "."
        return os.path.join(d, f"flight_recorder_{os.getpid()}{suffix}")

    def doc(self, reason: str = "explicit",
            trip_step: Optional[int] = None,
            extra: Optional[dict] = None) -> dict:
        """The dump document as a JSON-safe dict — exactly what
        :meth:`dump` writes. Factored out so the admin server's
        ``/debug/flight`` serves the SAME payload a crash would leave
        on disk, without touching the filesystem."""
        with self._lock:
            steps = [dict(r) for r in self._steps]
            events = [dict(r) for r in self._events]
        for r in steps + events:
            for k, v in r.items():
                r[k] = _json_safe(v)
        doc = {"reason": reason,
               "trip_step": trip_step,
               "dumped_at": time.time(),
               "fingerprint": self.fingerprint(),
               "flags": self._flags_snapshot(),
               "capacity": self.capacity,
               "steps": steps,
               "events": events}
        if extra:
            doc.update({k: _json_safe(v) for k, v in extra.items()})
        for key, provider in list(_DUMP_PROVIDERS.items()):
            try:
                # deep-sanitize: one non-finite float anywhere in a
                # provider's tree must not sink the whole crash dump
                # at json.dump(allow_nan=False) time
                doc.setdefault(key, _json_safe_tree(provider()))
            except Exception:
                pass               # the dump itself must still land
        return doc

    def dump(self, path: Optional[str] = None, reason: str = "explicit",
             trip_step: Optional[int] = None,
             extra: Optional[dict] = None) -> str:
        """Serialize fingerprint + flags + ring contents to ``path``
        (default ``flight_recorder_<pid>.json`` in
        ``FLAGS_flight_recorder_dir`` or cwd). Overwrites: the newest
        state of THIS process is the record of interest. Returns the
        path written."""
        path = path or self.default_path()
        doc = self.doc(reason=reason, trip_step=trip_step, extra=extra)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, allow_nan=False)
        os.replace(tmp, path)          # atomic: a crash mid-dump never
        self.dump_count += 1           # leaves a truncated record
        return path

    # -- crash wiring ------------------------------------------------------
    def install(self, excepthook: bool = True,
                enable_faulthandler: bool = True) -> None:
        """Idempotent: chain ``sys.excepthook`` to dump on unhandled
        exceptions, and point :mod:`faulthandler` at a sidecar file for
        crashes python never sees."""
        if self._installed:
            return
        self._installed = True
        if excepthook:
            self._prev_excepthook = sys.excepthook

            def hook(exc_type, exc, tb):
                try:
                    self.dump(reason="unhandled_exception",
                              extra={"exception":
                                     f"{exc_type.__name__}: {exc}"})
                except Exception:
                    pass               # the original traceback must win
                (self._prev_excepthook or sys.__excepthook__)(
                    exc_type, exc, tb)

            sys.excepthook = hook
        if enable_faulthandler:
            import faulthandler
            try:
                # remember whether someone else (pytest, the user) had
                # faulthandler on: uninstall() must give it back
                self._faulthandler_was_enabled = faulthandler.is_enabled()
                self._faulthandler_file = open(
                    self.default_path(suffix=".traceback"), "w")
                faulthandler.enable(file=self._faulthandler_file)
            except Exception:
                self._faulthandler_file = None

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._faulthandler_file is not None:
            import faulthandler
            try:
                if getattr(self, "_faulthandler_was_enabled", False):
                    faulthandler.enable()      # back to stderr, as before
                else:
                    faulthandler.disable()
                self._faulthandler_file.close()
            except Exception:
                pass
            self._faulthandler_file = None


# ---------------------------------------------------------------------------
# Process-global recorder
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_rec_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    """The process-global recorder (created on first use)."""
    global _recorder
    with _rec_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def set_flight_recorder(recorder: Optional[FlightRecorder]) \
        -> Optional[FlightRecorder]:
    """Swap the process-global recorder (tests); returns the old one."""
    global _recorder
    with _rec_lock:
        old, _recorder = _recorder, recorder
        return old


def enabled() -> bool:
    """True when TrainStep should record steps: ``FLAGS_monitor`` or
    ``FLAGS_flight_recorder``."""
    from ..core.flags import get_flag
    return bool(get_flag("monitor")) or bool(get_flag("flight_recorder"))


def safe_record_event(event: str, **fields) -> None:
    """Best-effort flight event: no-op unless recording is enabled
    (same gate as TrainStep records), and never raises — forensics must
    not take the emitting loop down. The one helper behind every
    guarded ``record_event`` call site (checkpoint fallbacks, serving
    lifecycle, collective timeouts)."""
    try:
        if not enabled():
            return
        get_flight_recorder().record_event(event, **fields)
    except Exception:
        pass


def trip_dump(step: Optional[int] = None, reason: str = "nan_watchdog",
              **info) -> Optional[str]:
    """Dump the global recorder on a watchdog trip (best-effort: a
    forensics write must never mask the error being raised). Returns
    the dump path, or None when the dump itself failed."""
    try:
        fr = get_flight_recorder()
        fr.record_event("trip", reason=reason, step=step, **info)
        return fr.dump(reason=reason, trip_step=step, extra=info)
    except Exception:
        return None


def load_dump(path: str) -> dict:
    """Parse a flight-recorder dump file."""
    with open(path) as f:
        return json.load(f)
