"""In-memory timeseries ring: registry snapshots over time, so the live
telemetry plane can show *movement*, not just cumulative counters
(ISSUE 14; docs/OBSERVABILITY.md "Live telemetry plane").

The metrics registry holds the CURRENT value of every series; an
operator watching a live process wants tokens/s, requests/s, burn — the
derivative. :class:`TimeseriesRing` keeps a bounded ring of
``(t, value)`` points per series, appended by :meth:`snapshot` (called
per ``/metrics``/``/statusz`` scrape by the admin server, or per
redraw by ``tools/monitor_top.py``), and answers:

- :meth:`rate` — Δvalue/Δt over a trailing window (counter semantics:
  a negative delta means the writer restarted, so the window restarts
  at the newest segment instead of reporting a negative rate);
- :meth:`delta` — plain Δvalue over the window;
- :meth:`latest` / :meth:`series` — current value / the raw points.

Histograms flatten into their Prometheus sample names: ``<name>_count``
and ``<name>_sum`` plus one cumulative ``<name>_bucket`` series per
``le`` bound (ISSUE 18), so ``rate("serve_e2e_seconds_count")`` is
completions/s, ``delta(sum)/delta(count)`` is the windowed mean
latency, and :meth:`quantile` interpolates a WINDOWED p50/p99 off the
bucket deltas (counter-reset folding applies to bucket series exactly
as to any counter — a restarted replica's scrape cannot yield negative
bucket mass).

Everything is host-side floats under one lock; a ring of 256 snapshots
of a few hundred series is ~100 KiB. Nothing here touches the registry
unless :meth:`snapshot` is called — the zero-overhead contract of the
monitor-off path is untouched.

:func:`parse_prometheus` is the inverse of
``MetricsRegistry.to_prometheus`` for the subset the ring needs
(counter/gauge samples + histogram ``_count``/``_sum``/``_bucket``
lines) — it lets ``tools/monitor_top.py`` and the fleet federator feed
a ring from a scraped ``/metrics`` page of ANY process, not just this
one.
"""

from __future__ import annotations

import collections
import math
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

# grammar atoms shared with the conformance lint (metrics.py) — the
# lenient parser and the strict lint must never drift apart
from .metrics import _L_LABEL_NAME, _L_METRIC_NAME, _L_NUM

__all__ = ["TimeseriesRing", "parse_prometheus"]

_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


class TimeseriesRing:
    """Bounded per-series history of registry (or scraped) samples."""

    def __init__(self, capacity: int = 256, clock=time.time):
        self.capacity = max(2, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._series: Dict[_SeriesKey, collections.deque] = {}
        self._kinds: Dict[str, str] = {}
        self.snapshots_taken = 0

    # -- ingestion ----------------------------------------------------------
    def snapshot(self, registry=None, t: Optional[float] = None) -> int:
        """Append one point per series from ``registry`` (default: the
        active :func:`~paddle_tpu.monitor.metrics.get_registry`).
        Returns the number of series touched."""
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        now = self.clock() if t is None else float(t)
        rows = []
        for name, info in registry.snapshot().items():
            kind = info["type"]
            for labels, value in info["samples"]:
                if kind == "histogram":
                    rows.append((f"{name}_count", labels, "counter",
                                 float(value["count"])))
                    rows.append((f"{name}_sum", labels, "counter",
                                 float(value["sum"])))
                    # per-bucket cumulative series on the exposition's
                    # exact `le` grid — the windowed bucket deltas
                    # `quantile` interpolates over
                    for le, cum in value["buckets"]:
                        rows.append((f"{name}_bucket",
                                     dict(labels, le=repr(float(le))),
                                     "counter", float(cum)))
                    rows.append((f"{name}_bucket",
                                 dict(labels, le="+Inf"),
                                 "counter", float(value["count"])))
                else:
                    rows.append((name, labels, kind, float(value)))
        return self._ingest(rows, now)

    def ingest_rows(self, rows: List[dict],
                    t: Optional[float] = None) -> int:
        """Append points from :func:`parse_prometheus` output (dicts
        with ``name``/``labels``/``type``/``value``)."""
        now = self.clock() if t is None else float(t)
        return self._ingest(
            [(r["name"], r.get("labels") or {}, r.get("type", "gauge"),
              float(r["value"])) for r in rows
             if isinstance(r.get("value"), (int, float))], now)

    def _ingest(self, rows, now: float) -> int:
        with self._lock:
            for name, labels, kind, value in rows:
                key = (name, tuple(sorted(
                    (k, str(v)) for k, v in dict(labels).items())))
                dq = self._series.get(key)
                if dq is None:
                    dq = self._series[key] = collections.deque(
                        maxlen=self.capacity)
                dq.append((now, value))
                self._kinds[name] = kind
            self.snapshots_taken += 1
            return len(rows)

    # -- reads --------------------------------------------------------------
    def _key(self, name: str, labels: dict) -> _SeriesKey:
        return (name, tuple(sorted((k, str(v))
                                   for k, v in labels.items())))

    def names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def kind(self, name: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(name)

    def series(self, name: str, **labels) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._series.get(self._key(name, labels), ()))

    def label_sets(self, name: str) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(k[1]) for k in self._series if k[0] == name]

    def latest(self, name: str, **labels) -> Optional[float]:
        pts = self.series(name, **labels)
        return pts[-1][1] if pts else None

    def _window(self, name: str, window_s: Optional[float],
                labels: dict) -> List[Tuple[float, float]]:
        pts = self.series(name, **labels)
        if window_s is None or not pts:
            return pts
        lo = pts[-1][0] - float(window_s)
        return [p for p in pts if p[0] >= lo]

    def delta(self, name: str, window_s: Optional[float] = None,
              **labels) -> Optional[float]:
        """newest − oldest value inside the trailing window (None with
        < 2 points). Counter resets (negative segments) are folded out
        the same way :meth:`rate` folds them."""
        pts = self._window(name, window_s, labels)
        if len(pts) < 2:
            return None
        total = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            if b >= a:
                total += b - a
            # else: writer restarted; the post-reset segment counts
            # from its own baseline (b - 0 would over-credit partial
            # scrapes, so the reset gap itself contributes nothing)
        return total

    def rate(self, name: str, window_s: Optional[float] = None,
             **labels) -> Optional[float]:
        """Per-second rate over the trailing window: Δvalue/Δt with
        counter-reset folding. None with < 2 points or zero time span."""
        pts = self._window(name, window_s, labels)
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        d = self.delta(name, window_s, **labels)
        return None if d is None else d / span

    def quantile(self, name: str, q: float,
                 window_s: Optional[float] = None,
                 **labels) -> Optional[float]:
        """WINDOWED quantile interpolated from ``<name>_bucket`` deltas
        (Prometheus ``histogram_quantile`` semantics: linear inside the
        winning bucket, the last finite bound when q lands in +Inf).
        Counter resets fold out per bucket series, so a restarted
        writer shrinks the window's mass instead of corrupting it.
        None when no bucket series match or the window saw no
        observations — a quantile over nothing is not 0.0."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        bname = f"{name}_bucket"
        want = {k: str(v) for k, v in labels.items()}
        grid: List[Tuple[float, float]] = []
        for ls in self.label_sets(bname):
            le = ls.get("le")
            if le is None:
                continue
            if {k: v for k, v in ls.items() if k != "le"} != want:
                continue
            d = self.delta(bname, window_s, **ls)
            if d is None:
                continue
            grid.append((math.inf if le == "+Inf" else float(le), d))
        if not grid:
            return None
        grid.sort()
        total = grid[-1][1]
        if total <= 0:
            return None
        target = q * total
        prev_b, prev_c = 0.0, 0.0
        for bound, cum in grid:
            if cum >= target:
                if math.isinf(bound):
                    return prev_b  # last finite bound, like Prometheus
                if cum <= prev_c:
                    return bound
                lo = prev_b if prev_c > 0 or bound <= 0 else 0.0
                return lo + (bound - lo) * (target - prev_c) \
                    / (cum - prev_c)
            prev_b, prev_c = bound, cum
        return prev_b

    def rates(self, window_s: Optional[float] = None) -> Dict[str, float]:
        """{``name{label=v,...}``: per-second rate} for every COUNTER
        series with enough history — the ``/statusz`` movement view.
        Histogram ``_bucket`` series are left out (a 16-bound grid per
        histogram would drown the page; read them via
        :meth:`quantile`)."""
        with self._lock:
            keys = list(self._series)
            kinds = dict(self._kinds)
        out: Dict[str, float] = {}
        for name, labels in keys:
            if kinds.get(name) != "counter" or name.endswith("_bucket"):
                continue
            r = self.rate(name, window_s, **dict(labels))
            if r is None:
                continue
            lbl = ",".join(f"{k}={v}" for k, v in labels)
            out[f"{name}{{{lbl}}}" if lbl else name] = r
        return out

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._kinds.clear()
            self.snapshots_taken = 0


# ---------------------------------------------------------------------------
# Prometheus text parsing (the monitor_top scrape path)
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(r"^# TYPE (\S+) (\S+)$")
_SAMPLE_RE = re.compile(
    rf"^({_L_METRIC_NAME})"
    r"(?:\{(.*?)\})?"
    rf" ({_L_NUM})"
    r"(?: [+-]?[0-9]+)?"
    r"(?: # .*)?$")
_LABEL_RE = re.compile(
    rf'({_L_LABEL_NAME})="((?:[^"\\]|\\.)*)"')


_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape(v: str) -> str:
    """Single-pass inverse of the exposition label escaping: sequential
    str.replace cannot decode this (``\\\\`` followed by a literal
    ``n`` would be misread as ``\\n``); a scanner consumes each escape
    pair exactly once. Unknown escapes pass through literally."""
    return _UNESCAPE_RE.sub(
        lambda m: {"\\": "\\", '"': '"', "n": "\n"}.get(
            m.group(1), m.group(0)), v)


def parse_prometheus(text: str) -> List[dict]:
    """Parse a text exposition page into rows shaped like
    ``load_jsonl`` output: ``{name, type, labels, value}``. Histogram
    samples come back as their flattened ``_count``/``_sum``/``_bucket``
    counter rows (ISSUE 18 — the fleet federator and :meth:`quantile`
    need the bucket grid); exemplar suffixes are ignored; unparseable
    lines are skipped (a scrape of a foreign process must degrade, not
    crash)."""
    rows: List[dict] = []
    kinds: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                kinds[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labelstr, value = m.group(1), m.group(2), m.group(3)
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        kind = kinds.get(name)
        if kind is None:
            for suffix in ("_bucket", "_count", "_sum"):
                if name.endswith(suffix) and \
                        kinds.get(name[:-len(suffix)]) == "histogram":
                    kind = "counter"
                    break
        try:
            rows.append({"name": name, "type": kind or "gauge",
                         "labels": labels, "value": float(value)})
        except ValueError:
            continue
    return rows
