"""SLO error-budget burn rate: multi-window gauges over serving
outcomes (ISSUE 11; the alerting-ready companion to the PR 8 overload
detector).

An SLO is an objective over a compliance period — "99.9% of requests
complete" over 30 days. The **error budget** is the allowed failure
fraction (``1 - objective``); the **burn rate** is how fast current
traffic spends it::

    burn_rate(window) = error_ratio(window) / (1 - objective)

Burn 1.0 = exactly on budget (the budget lasts the whole period);
burn 14.4 on a 99.9% SLO = the month's budget gone in ~2 days. The
Google SRE-workbook alerting recipe pairs a LONG window (is it real?)
with a SHORT one (is it still happening?) at the same threshold —
:meth:`SLOTracker.should_alert` implements exactly that, and
:meth:`SLOTracker.publish` exports ``slo_burn_rate{slo,window}`` /
``slo_error_budget_remaining{slo}`` gauges for dashboards.

:class:`SLOTracker` is pure host-side arithmetic over a bounded ring of
time buckets (injectable clock — the tests drive it deterministically).
The serving engine feeds two trackers when ``ServingConfig.slo_*``
objectives are set (off by default: zero tracker allocations, zero
registry writes — docs/SERVING.md):

- **availability**: good = completed; bad = expired / failed / shed
  (cancelled and drained are client/operator choices, not failures);
- **deadline**: good = completed with non-negative deadline slack;
  bad = completed late or expired in flight — fed from the same
  boundary that observes ``serve_deadline_slack_seconds``.
"""

from __future__ import annotations

import collections
import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["SLOTracker", "DEFAULT_WINDOWS", "DEFAULT_ALERT_PAIRS"]

#: default burn-rate windows (seconds): 5 min / 1 h / 6 h
DEFAULT_WINDOWS = (300.0, 3600.0, 21600.0)

#: SRE-workbook multiwindow multi-burn alert pairs:
#: (long_window_s, short_window_s, burn_threshold)
DEFAULT_ALERT_PAIRS = ((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))


class SLOTracker:
    """Sliding-window good/bad accounting for one SLO.

    Events land in fixed-resolution time buckets (default: fine enough
    for 60 buckets across the smallest window); windowed ratios read
    the ring, period totals are plain counters. O(1) per event, O(ring)
    per read — reads happen at dashboards' pace, not traffic's."""

    def __init__(self, name: str, objective: float,
                 windows: Sequence[float] = DEFAULT_WINDOWS,
                 period_s: float = 30 * 86400.0,
                 resolution_s: Optional[float] = None,
                 clock=time.monotonic):
        if not (0.0 < objective < 1.0):
            raise ValueError(f"SLO objective must be in (0, 1), got "
                             f"{objective} (it is a fraction, not a %)")
        if not windows:
            raise ValueError("SLOTracker needs >= 1 burn-rate window")
        self.name = name
        self.objective = float(objective)
        self.budget = 1.0 - float(objective)
        self.windows = tuple(sorted(float(w) for w in windows))
        if any(w <= 0 for w in self.windows):
            raise ValueError("burn-rate windows must be > 0 seconds")
        self.period_s = float(period_s)
        self.resolution_s = float(resolution_s) if resolution_s \
            else max(self.windows[0] / 60.0, 1.0)
        self.clock = clock
        # ring of [bucket_index, good, bad]; bounded by the largest
        # window (plus one bucket of slack for the partial edge)
        maxlen = int(math.ceil(self.windows[-1] / self.resolution_s)) + 1
        self._buckets: collections.deque = collections.deque(
            maxlen=maxlen)
        self.total_good = 0
        self.total_bad = 0

    # -- recording ----------------------------------------------------------
    def record(self, good: int = 0, bad: int = 0,
               t: Optional[float] = None) -> None:
        if good < 0 or bad < 0:
            raise ValueError("good/bad counts must be >= 0")
        if not (good or bad):
            return
        now = self.clock() if t is None else float(t)
        idx = int(now // self.resolution_s)
        if self._buckets and self._buckets[-1][0] == idx:
            b = self._buckets[-1]
            b[1] += good
            b[2] += bad
        else:
            if self._buckets and idx < self._buckets[-1][0]:
                # clock went backwards (test clocks, NTP): fold into the
                # newest bucket rather than corrupting ring order
                b = self._buckets[-1]
                b[1] += good
                b[2] += bad
            else:
                self._buckets.append([idx, good, bad])
        self.total_good += good
        self.total_bad += bad

    # -- reads --------------------------------------------------------------
    def _window_counts(self, window_s: float,
                       t: Optional[float] = None) -> Tuple[int, int]:
        now = self.clock() if t is None else float(t)
        lo = (now - float(window_s)) // self.resolution_s
        good = bad = 0
        for idx, g, b in self._buckets:
            if idx > lo:
                good += g
                bad += b
        return good, bad

    def error_ratio(self, window_s: float,
                    t: Optional[float] = None) -> float:
        """bad / (good + bad) over the window; 0.0 with no traffic (no
        traffic spends no budget)."""
        good, bad = self._window_counts(window_s, t)
        total = good + bad
        return bad / total if total else 0.0

    def burn_rate(self, window_s: float,
                  t: Optional[float] = None) -> float:
        """error_ratio / budget: 1.0 = spending exactly the budget."""
        return self.error_ratio(window_s, t) / self.budget

    def budget_remaining(self) -> float:
        """Fraction of the period's error budget left, from the period
        totals: 1.0 untouched, 0.0 exhausted, negative = blown."""
        total = self.total_good + self.total_bad
        if not total:
            return 1.0
        consumed = (self.total_bad / total) / self.budget
        return 1.0 - consumed

    def should_alert(self, pairs: Sequence[Tuple[float, float, float]]
                     = DEFAULT_ALERT_PAIRS,
                     t: Optional[float] = None) -> List[dict]:
        """Multiwindow multi-burn: a pair fires when BOTH its long and
        short windows burn above the threshold (long = significant,
        short = still happening). Returns the firing pairs (empty =
        healthy)."""
        out = []
        for long_w, short_w, thr in pairs:
            bl = self.burn_rate(long_w, t)
            bs = self.burn_rate(short_w, t)
            if bl >= thr and bs >= thr:
                out.append({"long_window_s": long_w,
                            "short_window_s": short_w,
                            "threshold": thr, "long_burn": bl,
                            "short_burn": bs})
        return out

    # -- export -------------------------------------------------------------
    def publish(self, registry=None, t: Optional[float] = None) -> None:
        """Export the burn gauges: ``slo_burn_rate{slo,window}`` per
        configured window, ``slo_error_budget_remaining{slo}`` and
        ``slo_objective{slo}``."""
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        g = registry.gauge(
            "slo_burn_rate",
            "error-budget burn rate by SLO and window (1.0 = spending "
            "exactly the budget)")
        for w in self.windows:
            g.set(self.burn_rate(w, t), slo=self.name, window=f"{w:g}s")
        registry.gauge(
            "slo_error_budget_remaining",
            "fraction of the period error budget left (negative = "
            "blown)").set(self.budget_remaining(), slo=self.name)
        registry.gauge(
            "slo_objective", "configured SLO objective fraction").set(
            self.objective, slo=self.name)

    def snapshot(self) -> Dict[str, float]:
        d: Dict[str, float] = {
            "objective": self.objective,
            "budget_remaining": self.budget_remaining(),
            "total_good": float(self.total_good),
            "total_bad": float(self.total_bad)}
        for w in self.windows:
            d[f"burn_{w:g}s"] = self.burn_rate(w)
        return d
