"""Fleet observability plane: cross-replica trace merging, live
metrics federation, and anomaly-triggered incident capture (ISSUE 18;
docs/OBSERVABILITY.md "Fleet observability").

PR 14's fleet router scattered one request's story across processes:
the routing decision lived in the router's registry, the serve spans in
whichever replica held the request (a different one after each
migration), and nothing joined them back together. This module is the
join, in three layers:

- **trace merge** — :func:`merge_fleet_traces` folds per-process trace
  docs that share a ``trace_id`` into ONE span tree: span ids are
  qualified by each doc's ``ctx`` namespace (``"<ctx>/<span_id>"``, so
  two processes' span #3 never collide), a doc whose ``parent_ctx``
  token resolves inside the group parents its root there (the Dapper
  join the router's ``Request.trace_parent`` propagation set up), and
  every span is stamped with its producing ``process`` so
  :func:`~.trace.perfetto_doc` renders one Perfetto track per process.
  ``tools/monitor_report.py --trace`` renders merged docs unchanged —
  its tree walk only needs ids to be *consistent*, not integers.

- **metrics federation** — :class:`FleetFederator` runs a stdlib
  scrape loop over :class:`FleetTarget`\\ s (replica ``/metrics`` URLs,
  or callables for in-process fleets), parses each page with
  :func:`~.timeseries.parse_prometheus`, stamps every sample with a
  ``host`` label, and REBUILDS the fleet registry from scratch each
  scrape (cumulative pages re-merged into a persistent registry would
  double-count; a rebuild makes the federated page exactly the sum of
  the per-replica pages, restart-safe). The fleet registry feeds a
  :class:`~.timeseries.TimeseriesRing` (windowed fleet rates, windowed
  quantiles off the federated ``_bucket`` series) and an embedded
  :class:`~.server.AdminServer`: ``/metrics`` (lint-clean,
  host-labelled), ``/statusz`` (per-replica table + per-tenant
  rollup), ``/healthz``, ``/readyz`` (quorum of replica readiness) and
  ``/debug/trace`` (the MERGED fleet trace view).

- **SLO burn + incident capture** — an optional
  :class:`~.slo.SLOTracker` is fed from the federated
  ``serve_requests_total{host,event}`` deltas (reset-folded: a
  restarted replica's counters shrink nothing). When a multiwindow
  burn alert fires, or a tail-retained anomaly trace lands
  (:data:`~.trace.TRACE_STATS` ``tail_retained`` moved), the federator
  captures a **bounded-rate incident bundle** — the implicated
  replica's flight-recorder doc, the merged Perfetto trace, the fleet
  statusz snapshot and the federated metrics page — into a timestamped
  ``incident_*`` directory. One bundle per
  ``incident_min_interval_s``; an alert storm produces ONE bundle and
  a counter, not a disk full of them.

Zero-overhead contract (the PR 13 pattern): every entry point here is
reached through :func:`maybe_start_from_flags`, which reads ONE flag
(``FLAGS_fleet_monitor_port``) and returns None when it is 0 (the
default) — no thread, no socket, no registry series, and the router
fast path never allocates a fleet object. Pinned by test.

Security: the federator binds ``FLAGS_monitor_host`` (127.0.0.1 by
default) and *fetches* from operator-configured target URLs — it is an
aggregation point for everything the per-process planes expose, so the
same bind-address caution applies doubly (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import trace as trace_mod
from .metrics import MetricsRegistry, _label_key
from .server import AdminServer
from .slo import DEFAULT_ALERT_PAIRS, DEFAULT_WINDOWS, SLOTracker
from .timeseries import TimeseriesRing, parse_prometheus

__all__ = [
    "FleetTarget", "FederatorConfig", "FleetFederator",
    "merge_fleet_traces", "maybe_start_from_flags", "get_federator",
    "stop_federator", "SCRAPE_THREAD_PREFIX",
]

#: thread-name prefix of the federator's scrape loop — the fleet
#: zero-thread pin greps live thread names for this (the embedded admin
#: plane's threads carry server.THREAD_PREFIX already)
SCRAPE_THREAD_PREFIX = "ptpu-fleet"

#: availability vocabulary over serve_requests_total{event=...}:
#: cancelled/drained are client/operator choices and spend no budget
#: (matching the engine's own SLO feed, monitor/slo.py)
GOOD_EVENTS = ("completed",)
BAD_EVENTS = ("expired", "failed", "shed", "rejected")

_FETCH_TIMEOUT_S = 2.0


# ---------------------------------------------------------------------------
# Cross-process trace merging
# ---------------------------------------------------------------------------


def merge_fleet_traces(docs: Sequence[dict]) -> List[dict]:
    """Fold trace docs sharing a ``trace_id`` into single span trees.

    A doc that is alone under its trace_id and carries no
    ``parent_ctx`` passes through UNTOUCHED (integer span ids and all —
    single-process dumps render byte-identically). Groups merge under
    ctx-qualified string span ids; a doc root whose ``parent_ctx``
    token exists in the group parents there, otherwise it stays a root
    (its upstream process' buffer was lost — the subtree still
    renders). Merged docs carry ``merged_from`` (doc count) and
    ``processes`` (producing process labels, root-doc first); anomaly
    is the first non-None reason, ``head_sampled`` is any, ``finished``
    is all."""
    groups: Dict[Any, List[dict]] = {}
    for d in docs:
        groups.setdefault(d.get("trace_id"), []).append(d)
    out: List[dict] = []
    for trace_id, group in groups.items():
        if len(group) == 1 and not group[0].get("parent_ctx"):
            out.append(group[0])
            continue
        out.append(_merge_group(trace_id, group))
    return out


def _merge_group(trace_id: Any, group: List[dict]) -> dict:
    known = set()
    for d in group:
        ctx = d.get("ctx") or ""
        for s in d.get("spans") or ():
            known.add(f"{ctx}/{s.get('span_id')}")
    root_doc = None
    for d in group:
        pc = d.get("parent_ctx")
        if pc is None or pc not in known:
            root_doc = d
            break
    if root_doc is None:         # a parent cycle can only come from a
        root_doc = group[0]      # corrupt dump; degrade, don't crash
    spans: List[dict] = []
    processes: List[str] = []
    anomaly = None
    head_sampled = False
    finished = True
    for d in sorted(group, key=lambda d: 0 if d is root_doc else 1):
        ctx = d.get("ctx") or ""
        proc = d.get("process")
        if proc is not None and proc not in processes:
            processes.append(proc)
        if anomaly is None:
            anomaly = d.get("anomaly")
        head_sampled = head_sampled or bool(d.get("head_sampled"))
        finished = finished and bool(d.get("finished"))
        pc = d.get("parent_ctx")
        for s in d.get("spans") or ():
            ns = dict(s)
            ns["span_id"] = f"{ctx}/{s.get('span_id')}"
            pid = s.get("parent_id")
            if pid is None:
                # the doc's own root: parent it at the upstream token
                # when that span made it into the group
                ns["parent_id"] = pc if pc in known else None
            else:
                ns["parent_id"] = f"{ctx}/{pid}"
            if proc is not None:
                ns["process"] = proc
            spans.append(ns)
    return {"trace_id": trace_id, "name": root_doc.get("name"),
            "head_sampled": head_sampled, "anomaly": anomaly,
            "finished": finished, "spans": spans,
            "merged_from": len(group), "processes": processes}


# ---------------------------------------------------------------------------
# Scrape targets
# ---------------------------------------------------------------------------


@dataclass
class FleetTarget:
    """One federation target: a replica (or router) admin plane.

    ``url`` is the plane's base (``http://host:port``; ``/metrics``,
    ``/readyz`` and ``/debug/*`` derive from it). In-process fleets
    pass callables instead: ``fetch_metrics()`` returns an exposition
    page, ``fetch_ready()`` True/False, ``fetch_debug(path)`` a parsed
    JSON doc (or None)."""

    name: str
    url: Optional[str] = None
    fetch_metrics: Optional[Callable[[], str]] = None
    fetch_ready: Optional[Callable[[], bool]] = None
    fetch_debug: Optional[Callable[[str], Optional[dict]]] = None

    def metrics_text(self) -> str:
        if self.fetch_metrics is not None:
            return self.fetch_metrics()
        if self.url is None:
            raise ValueError(f"target {self.name!r}: no url and no "
                             "fetch_metrics callable")
        with urllib.request.urlopen(f"{self.url}/metrics",
                                    timeout=_FETCH_TIMEOUT_S) as r:
            return r.read().decode("utf-8", "replace")

    def ready(self) -> bool:
        if self.fetch_ready is not None:
            return bool(self.fetch_ready())
        if self.url is None:
            return True          # a callable-only target that answered
        try:                     # its scrape counts as ready
            with urllib.request.urlopen(f"{self.url}/readyz",
                                        timeout=_FETCH_TIMEOUT_S) as r:
                return r.status == 200
        except urllib.error.HTTPError as e:
            return e.code == 200
        except Exception:
            return False

    def debug_doc(self, path: str) -> Optional[dict]:
        """Fetch ``/debug/<path>`` as parsed JSON (None on any
        failure — incident capture is best-effort per artifact)."""
        try:
            if self.fetch_debug is not None:
                return self.fetch_debug(path)
            if self.url is None:
                return None
            with urllib.request.urlopen(f"{self.url}/debug/{path}",
                                        timeout=_FETCH_TIMEOUT_S) as r:
                return json.loads(r.read().decode("utf-8", "replace"))
        except Exception:
            return None


def parse_targets(spec: str) -> List[FleetTarget]:
    """``'name=http://host:port,...'`` → targets (the
    ``FLAGS_fleet_monitor_targets`` format). A bare URL gets its
    ``host:port`` as the name."""
    out: List[FleetTarget] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, url = part.split("=", 1)
        else:
            name, url = part.split("//")[-1].rstrip("/"), part
        out.append(FleetTarget(name=name.strip(),
                               url=url.strip().rstrip("/")))
    return out


def local_registry_target(name: str = "fleet") -> FleetTarget:
    """The in-process default (``FLAGS_fleet_monitor_targets`` empty):
    federate the process-global registry — the shape of an in-process
    fleet, where router and replicas already share one registry."""
    def _fetch() -> str:
        from .metrics import get_registry
        return get_registry().to_prometheus()

    def _debug(path: str) -> Optional[dict]:
        if path.startswith("flight"):
            from .flight_recorder import get_flight_recorder
            return get_flight_recorder().doc(reason="fleet_incident")
        return None

    return FleetTarget(name=name, fetch_metrics=_fetch,
                       fetch_debug=_debug)


# ---------------------------------------------------------------------------
# Federator
# ---------------------------------------------------------------------------


@dataclass
class FederatorConfig:
    #: scrape period (the loop's cadence; scrape_once() is also public
    #: for deterministic tests)
    interval_s: float = 1.0
    #: replicas that must be ready for fleet /readyz; None = majority
    quorum: Optional[int] = None
    #: fleet availability SLO objective fraction; 0.0 = no tracker
    slo_availability: float = 0.0
    slo_windows: Sequence[float] = DEFAULT_WINDOWS
    alert_pairs: Sequence[Tuple[float, float, float]] = \
        DEFAULT_ALERT_PAIRS
    #: where incident bundles land; None = incident capture off
    incident_dir: Optional[str] = None
    #: floor between bundles — an alert storm yields ONE bundle
    incident_min_interval_s: float = 300.0
    #: also capture when a tail-retained anomaly trace lands
    capture_on_anomaly: bool = True
    #: trailing window for /statusz fleet rates + quantiles
    window_s: float = 60.0


class FleetFederator:
    """The fleet scrape loop + its admin plane. ``router=`` optionally
    attaches a live :class:`~..serving.router.FleetRouter` so the
    ``/statusz`` replica table carries its authoritative per-replica
    view (free pages, alive/draining state) next to the scraped one."""

    def __init__(self, targets: Sequence[FleetTarget],
                 config: Optional[FederatorConfig] = None,
                 router=None, port: Optional[int] = None,
                 host: str = "127.0.0.1", clock=time.time):
        if not targets:
            raise ValueError("FleetFederator needs >= 1 target")
        names = [t.name for t in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate target names {names} — the "
                             "host label must identify ONE replica")
        self.targets = list(targets)
        self.config = config or FederatorConfig()
        self.router = router
        self.clock = clock
        #: the federated registry — REBUILT from the target pages every
        #: scrape (never written between scrapes)
        self.registry = MetricsRegistry()
        #: the federator's own telemetry, merged in after each rebuild
        self._own = MetricsRegistry()
        self.ring = TimeseriesRing(clock=clock)
        self.slo: Optional[SLOTracker] = None
        if self.config.slo_availability > 0.0:
            self.slo = SLOTracker(
                "fleet_availability", self.config.slo_availability,
                windows=self.config.slo_windows, clock=clock)
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._admin: Optional[AdminServer] = None
        self._admin_port = port
        self._admin_host = host
        #: last-seen serve_requests_total{host,event} values (the SLO
        #: delta baseline; resets fold to "count from new baseline")
        self._req_seen: Dict[Tuple[str, str], float] = {}
        #: per-scrape bad-event delta per host (implicates a replica)
        self._bad_delta: Dict[str, float] = {}
        self._target_state: Dict[str, str] = {
            t.name: "unscraped" for t in self.targets}
        self._last_incident_t: Optional[float] = None
        self._anomaly_seen = int(trace_mod.TRACE_STATS["tail_retained"])
        self.incidents: List[str] = []      # bundle dirs, oldest first

    # -- lifecycle ----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def url(self) -> Optional[str]:
        return self._admin.url if self._admin is not None else None

    def start(self) -> "FleetFederator":
        if self._admin is None and self._admin_port is not None:
            admin = _FleetAdmin(self, port=self._admin_port,
                                host=self._admin_host, clock=self.clock)
            admin.register_readiness("fleet_quorum", self._quorum_check)
            admin.register_status("fleet", self._fleet_status)
            admin.start()
            self._admin = admin
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"{SCRAPE_THREAD_PREFIX}-scrape", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        a, self._admin = self._admin, None
        if a is not None:
            a.close()

    def _loop(self) -> None:
        while not self._stop.wait(max(0.01, self.config.interval_s)):
            try:
                self.scrape_once()
            except Exception:
                pass             # one bad scrape must not kill the loop

    # -- the scrape ---------------------------------------------------------
    def scrape_once(self, t: Optional[float] = None) -> dict:
        """One federation round: fetch + parse every target page,
        rebuild the fleet registry, feed the ring and SLO tracker,
        check alerts/anomalies, maybe capture an incident. Returns a
        summary dict (tests drive this directly with an injected
        clock)."""
        now = self.clock() if t is None else float(t)
        pages: List[Tuple[FleetTarget, MetricsRegistry]] = []
        for tgt in self.targets:
            try:
                rows = parse_prometheus(tgt.metrics_text())
            except Exception:
                self._own.counter(
                    "fleet_scrape_errors_total",
                    "federation scrapes that failed, by target"
                ).inc(host=tgt.name)
                self._target_state[tgt.name] = "unreachable"
                continue
            self._own.counter(
                "fleet_scrapes_total",
                "federation scrapes completed, by target").inc(
                host=tgt.name)
            self._target_state[tgt.name] = (
                "ready" if tgt.ready() else "not_ready")
            pages.append((tgt, _registry_from_rows(rows, tgt.name)))
        states = list(self._target_state.values())
        g = self._own.gauge("fleet_replicas",
                            "federation targets by last-scrape state")
        for state in ("ready", "not_ready", "unreachable", "unscraped"):
            g.set(states.count(state), state=state)
        with self._lock:
            # rebuild-from-scratch: each target page is already
            # cumulative, so the federated page must be the SUM of the
            # current pages, not an accumulation over scrape history
            self.registry.clear()
            for _, reg in pages:
                self.registry.merge(reg)
            self.registry.merge(self._own)
            self._feed_slo(now)
            if self.slo is not None:
                self.slo.publish(self.registry, t=now)
            self.ring.snapshot(self.registry, t=now)
        alerts = (self.slo.should_alert(self.config.alert_pairs, t=now)
                  if self.slo is not None else [])
        anomalies = 0
        tail = int(trace_mod.TRACE_STATS["tail_retained"])
        if tail > self._anomaly_seen:
            anomalies = tail - self._anomaly_seen
        self._anomaly_seen = tail
        bundle = None
        if alerts:
            bundle = self.capture_incident("slo_burn", t=now,
                                           alerts=alerts)
        elif anomalies and self.config.capture_on_anomaly:
            bundle = self.capture_incident("anomaly_trace", t=now,
                                           anomalies=anomalies)
        return {"t": now, "targets_scraped": len(pages),
                "alerts": alerts, "anomalies": anomalies,
                "incident": bundle}

    def _feed_slo(self, now: float) -> None:
        """Feed availability good/bad from the federated
        serve_requests_total{host,event} deltas. Caller holds _lock."""
        ctr = self.registry.get("serve_requests_total")
        if ctr is None:
            return
        good = bad = 0
        for labels, value in ctr.samples():
            event = labels.get("event")
            if event not in GOOD_EVENTS and event not in BAD_EVENTS:
                continue
            key = (labels.get("host", ""), str(event))
            last = self._req_seen.get(key, 0.0)
            # reset folding: a restarted replica counts from its own
            # new baseline (the gap contributes nothing)
            delta = value - last if value >= last else value
            self._req_seen[key] = value
            if delta <= 0:
                continue
            if event in GOOD_EVENTS:
                good += int(delta)
            else:
                bad += int(delta)
                self._bad_delta[key[0]] = \
                    self._bad_delta.get(key[0], 0.0) + delta
        if self.slo is not None and (good or bad):
            self.slo.record(good=good, bad=bad, t=now)

    # -- fleet views --------------------------------------------------------
    def merged_traces(self) -> List[dict]:
        """Every trace doc the fleet can see — the local tracer's
        buffer plus each URL target's ``/debug/trace`` — merged by
        trace_id into single span trees."""
        docs = list(trace_mod.get_tracer().snapshot(include_live=True))
        for tgt in self.targets:
            if tgt.url is None and tgt.fetch_debug is None:
                continue
            d = tgt.debug_doc("trace")
            for td in (d or {}).get("traces") or ():
                docs.append(td)
        seen = set()
        unique = []
        for d in docs:           # a target sharing this process' tracer
            key = (d.get("trace_id"), d.get("ctx"))   # yields dupes
            if d.get("ctx") is not None and key in seen:
                continue
            seen.add(key)
            unique.append(d)
        return merge_fleet_traces(unique)

    def _quorum_check(self) -> Optional[dict]:
        ready = sum(1 for s in self._target_state.values()
                    if s == "ready")
        need = (self.config.quorum if self.config.quorum is not None
                else len(self.targets) // 2 + 1)
        if ready >= need:
            return None
        return {"state": "no-quorum", "ready": ready, "need": need,
                "targets": dict(self._target_state)}

    def _fleet_status(self) -> dict:
        """The /statusz 'fleet' section: one row per replica (scraped
        state + queue/pages/prefix-hit off the federated registry,
        free pages and aliveness from an attached router), a per-tenant
        rollup, windowed fleet rates and e2e quantiles."""
        w = self.config.window_s
        per: Dict[str, dict] = {}
        for tgt in self.targets:
            h = tgt.name
            row: Dict[str, Any] = {
                "state": self._target_state.get(h, "unscraped"),
                "queue_depth": self._gauge_val("serve_queue_depth", h),
                "kv_pages_in_use": self._gauge_val(
                    "serve_kv_pages_in_use", h),
                "overloaded": bool(self._gauge_val("serve_overload", h)
                                   or 0.0),
                "prefix_hit_pct": self._prefix_hit_pct(h),
            }
            per[h] = row
        if self.router is not None:
            try:
                for name, rep in self.router.replicas.items():
                    row = per.setdefault(name, {"state": "router-only"})
                    row["alive"] = rep.alive
                    if rep.alive:
                        s = rep.status()
                        row["free_pages"] = s.get("free_pages")
                        row.setdefault("queue_depth",
                                       s.get("queue_depth"))
            except Exception:
                pass
        doc: Dict[str, Any] = {
            "targets": per,
            "tenants": self._tenant_rollup(),
            "rates": {"window_s": w,
                      "per_second": self.ring.rates(window_s=w)},
        }
        for h in per:
            for q in (0.5, 0.99):
                v = self.ring.quantile("serve_e2e_seconds", q,
                                       window_s=w, host=h)
                if v is not None:
                    per[h][f"e2e_p{int(q * 100)}_s"] = v
        if self.slo is not None:
            doc["slo"] = self.slo.snapshot()
        if self.incidents:
            doc["incidents"] = list(self.incidents[-5:])
        return doc

    def _gauge_val(self, name: str, host: str) -> Optional[float]:
        with self._lock:
            m = self.registry.get(name)
            if m is None:
                return None
            for labels, value in m.samples():
                if labels.get("host") == host:
                    return float(value)
        return None

    def _counter_sum(self, name: str, host: str) -> float:
        total = 0.0
        with self._lock:
            m = self.registry.get(name)
            if m is None:
                return 0.0
            for labels, value in m.samples():
                if labels.get("host") == host:
                    total += float(value)
        return total

    def _prefix_hit_pct(self, host: str) -> Optional[float]:
        hits = self._counter_sum("serve_prefix_hits_total", host)
        misses = self._counter_sum("serve_prefix_misses_total", host)
        if hits + misses <= 0:
            return None
        return 100.0 * hits / (hits + misses)

    def _tenant_rollup(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        with self._lock:
            m = self.registry.get("serve_tenant_requests_total")
            if m is None:
                return out
            for labels, value in m.samples():
                tenant = labels.get("tenant", "?")
                row = out.setdefault(tenant, {})
                ev = labels.get("event", "?")
                row[ev] = row.get(ev, 0.0) + float(value)
        return out

    # -- incident capture ---------------------------------------------------
    def capture_incident(self, trigger: str,
                         t: Optional[float] = None,
                         **detail) -> Optional[str]:
        """Write one incident bundle (rate-limited). Returns the bundle
        dir, or None when capture is off / inside the rate floor."""
        if not self.config.incident_dir:
            return None
        now = self.clock() if t is None else float(t)
        with self._lock:
            if (self._last_incident_t is not None
                    and now - self._last_incident_t
                    < self.config.incident_min_interval_s):
                return None
            self._last_incident_t = now
        d = os.path.join(self.config.incident_dir,
                         f"incident_{int(now * 1000)}_{trigger}")
        os.makedirs(d, exist_ok=True)
        implicated = self._implicated_target()
        self._write_json(os.path.join(d, "incident.json"), {
            "trigger": trigger, "t": now,
            "implicated": implicated.name if implicated else None,
            "targets": dict(self._target_state),
            "slo": self.slo.snapshot() if self.slo else None,
            **detail})
        self._write_json(os.path.join(d, "statusz.json"),
                         self._fleet_status())
        with self._lock:
            page = self.registry.to_prometheus()
        with open(os.path.join(d, "metrics.prom"), "w") as f:
            f.write(page)
        flight = (implicated.debug_doc("flight")
                  if implicated is not None else None)
        if flight is None:       # fall back to the local recorder
            try:
                from .flight_recorder import get_flight_recorder
                flight = get_flight_recorder().doc(
                    reason=f"fleet_incident:{trigger}")
            except Exception:
                flight = None
        if flight is not None:
            self._write_json(os.path.join(d, "flight.json"), flight)
        try:
            self._write_json(
                os.path.join(d, "trace_perfetto.json"),
                trace_mod.perfetto_doc(self.merged_traces(),
                                       include_host_timeline=False))
        except Exception:
            pass
        self._own.counter(
            "fleet_incidents_total",
            "incident bundles captured, by trigger").inc(
            trigger=trigger)
        self.incidents.append(d)
        return d

    def _implicated_target(self) -> Optional[FleetTarget]:
        """The replica to pull forensics from: worst bad-event delta
        since the last incident, else the first unreachable/not-ready
        one, else the first target."""
        if self._bad_delta:
            worst = max(self._bad_delta, key=self._bad_delta.get)
            self._bad_delta.clear()
            for tgt in self.targets:
                if tgt.name == worst:
                    return tgt
        for state in ("unreachable", "not_ready"):
            for tgt in self.targets:
                if self._target_state.get(tgt.name) == state:
                    return tgt
        return self.targets[0] if self.targets else None

    @staticmethod
    def _write_json(path: str, doc: Any) -> None:
        from .flight_recorder import _json_safe_tree
        with open(path, "w") as f:
            json.dump(_json_safe_tree(doc), f, indent=1)


def _registry_from_rows(rows: List[dict],
                        host: str) -> MetricsRegistry:
    """A one-page registry with ``host=<name>`` stamped on EVERY
    sample — counters with distinct hosts stay distinct series, so
    merging the per-target registries sums nothing away."""
    reg = MetricsRegistry()
    for r in rows:
        kind = r.get("type")
        if kind not in ("counter", "gauge"):
            kind = "gauge"       # histograms arrive pre-flattened as
        try:                     # typed _bucket/_count/_sum counters
            m = reg._raw_metric(str(r["name"]), kind)
        except (TypeError, KeyError):
            continue
        labels = dict(r.get("labels") or {})
        labels["host"] = host
        m._series[_label_key(labels)] = float(r["value"])
    return reg


class _FleetAdmin(AdminServer):
    """The federator's admin plane: same endpoints as a replica's, but
    ``/metrics``//``/statusz`` read the FEDERATED registry/ring and
    ``/debug/trace`` serves the MERGED fleet trace view."""

    def __init__(self, fed: FleetFederator, **kw):
        super().__init__(registry=fed.registry, ring=fed.ring, **kw)
        self._fed = fed

    def _debug_trace(self, h, query) -> None:
        docs = self._fed.merged_traces()
        if query.get("format") == "perfetto":
            return self._json(h, trace_mod.perfetto_doc(docs))
        self._json(h, {"format": 1, "dumped_at": self.clock(),
                       "traces": docs})


# ---------------------------------------------------------------------------
# Flag-gated process-global federator
# ---------------------------------------------------------------------------

_federator: Optional[FleetFederator] = None
_federator_lock = threading.Lock()


def maybe_start_from_flags() -> Optional[FleetFederator]:
    """Start (or return) the process-global federator when
    ``FLAGS_fleet_monitor_port`` is set; None — after ONE flag read,
    zero allocations — when it is 0 (the default). ``-1`` binds an
    ephemeral port (read it back from ``get_federator().url``)."""
    from ..core.flags import get_flag
    port = int(get_flag("fleet_monitor_port") or 0)
    if port == 0:
        return None
    global _federator
    with _federator_lock:
        if _federator is None or not _federator.running:
            targets = parse_targets(
                str(get_flag("fleet_monitor_targets") or ""))
            if not targets:
                targets = [local_registry_target()]
            cfg = FederatorConfig(
                interval_s=float(
                    get_flag("fleet_monitor_interval_s") or 1.0),
                slo_availability=float(
                    get_flag("fleet_monitor_slo") or 0.0),
                incident_dir=(
                    str(get_flag("fleet_monitor_incident_dir") or "")
                    or None))
            host = str(get_flag("monitor_host") or "127.0.0.1")
            fed = FleetFederator(targets, cfg,
                                 port=(0 if port < 0 else port),
                                 host=host)
            try:
                fed.start()
            except OSError as e:
                import warnings
                warnings.warn(
                    f"fleet federator failed to bind {host}:{port} "
                    f"({e}); fleet plane disabled for this process",
                    RuntimeWarning)
                return None
            _federator = fed
        return _federator


def get_federator() -> Optional[FleetFederator]:
    """The process-global federator, if one is running."""
    return _federator


def stop_federator() -> None:
    """Tear down the process-global federator (tests / shutdown)."""
    global _federator
    with _federator_lock:
        if _federator is not None:
            _federator.close()
            _federator = None
