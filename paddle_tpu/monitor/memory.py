"""HBM memory accounting: per-program budgets, pre-flight checks, and a
live-buffer census.

Reference analogue: Paddle's allocator stats surface
(``paddle.device.cuda.memory_allocated / max_memory_allocated /
memory_summary`` over the BFC allocator counters). On TPU, XLA owns HBM,
so the framework-level answers come from two different places:

- **static budgets** from the compiled executable itself
  (``compiled.memory_analysis()``): argument / output / temp /
  generated-code bytes per TrainStep program kind, known BEFORE the
  first step runs — which is what makes an OOM *pre-flight* check
  possible (:func:`preflight_check`, gated by ``FLAGS_memory_preflight``);
- **live actuals** from the runtime (``device.memory_stats()`` where the
  backend publishes them, plus a :func:`live_buffer_census` over
  ``jax.live_arrays()`` that attributes bytes to params / optimizer
  state / activations / unattributed and lets :class:`LeakMonitor` flag
  step-over-step growth).

``memory_summary()`` renders both halves in the spirit of
``paddle.device.cuda.memory_summary``. See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import threading
import warnings
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = [
    "ProgramMemory", "MemoryBudgetError", "analyze_compiled",
    "record_program", "programs", "device_memory_stats", "device_hbm_bytes",
    "preflight_check", "live_buffer_census", "live_bytes",
    "publish_census", "LeakMonitor", "memory_summary", "fmt_bytes",
]


def fmt_bytes(n: Optional[float]) -> str:
    # same unit ladder as tools/monitor_report.py (the tool keeps a
    # standalone copy so it imports without the package on sys.path)
    if n is None:
        return "n/a"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}"
        n /= 1024
    return f"{n:,.1f} TiB"


class MemoryBudgetError(RuntimeError):
    """Pre-flight says the program will not fit device HBM; carries the
    numbers for programmatic handling."""

    def __init__(self, message: str, estimate_bytes: int = 0,
                 limit_bytes: int = 0):
        super().__init__(message)
        self.estimate_bytes = estimate_bytes
        self.limit_bytes = limit_bytes


@dataclass
class ProgramMemory:
    """Static HBM budget of ONE compiled executable, from XLA's
    ``memory_analysis()`` (CompiledMemoryStats)."""

    kind: str
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    generated_code_bytes: int = 0

    @property
    def peak_bytes(self) -> int:
        """Peak HBM the executable needs live at once: inputs + outputs
        + scratch + program text, minus input/output aliasing (donated
        buffers are counted once, not twice)."""
        return max(0, self.argument_bytes + self.output_bytes
                   + self.temp_bytes + self.generated_code_bytes
                   - self.alias_bytes)

    def as_dict(self) -> Dict[str, int]:
        return {"kind": self.kind,
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "alias_bytes": self.alias_bytes,
                "generated_code_bytes": self.generated_code_bytes,
                "peak_bytes": self.peak_bytes}


def analyze_compiled(compiled, kind: str = "step") \
        -> Optional[ProgramMemory]:
    """Extract a :class:`ProgramMemory` from a ``jax.stages.Compiled``;
    None when the backend publishes no memory analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def b(attr: str) -> int:
        return int(getattr(ma, attr, 0) or 0)

    return ProgramMemory(
        kind=kind,
        argument_bytes=b("argument_size_in_bytes"),
        output_bytes=b("output_size_in_bytes"),
        temp_bytes=b("temp_size_in_bytes"),
        alias_bytes=b("alias_size_in_bytes"),
        generated_code_bytes=b("generated_code_size_in_bytes"))


# ---------------------------------------------------------------------------
# Process-global program table (memory_summary's data source)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_PROGRAMS: Dict[str, ProgramMemory] = {}


def record_program(pm: ProgramMemory) -> None:
    """Register a compiled program's budget in the process-global table
    (newest executable per kind wins — a recompile replaces its entry)."""
    with _LOCK:
        _PROGRAMS[pm.kind] = pm


def programs() -> Dict[str, ProgramMemory]:
    """Snapshot of the process-global per-kind program budgets."""
    with _LOCK:
        return dict(_PROGRAMS)


# ---------------------------------------------------------------------------
# Device actuals
# ---------------------------------------------------------------------------

def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """Runtime allocator stats of ``device`` (default: first visible), or
    None where the backend publishes none (the CPU test backend)."""
    import jax
    try:
        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


def device_hbm_bytes(device=None) -> Optional[int]:
    """Total HBM the runtime will let us allocate, or None when unknown."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    return stats.get("bytes_limit") or stats.get("bytes_reservable_limit")


def _preflight_limit(limit_bytes: Optional[int], device) -> Optional[int]:
    if limit_bytes is not None:
        return int(limit_bytes)
    from ..core.flags import get_flag
    mb = int(get_flag("memory_preflight_limit_mb") or 0)
    if mb > 0:
        return mb << 20
    return device_hbm_bytes(device)


def preflight_check(program: "ProgramMemory | Dict[str, ProgramMemory]",
                    limit_bytes: Optional[int] = None, device=None,
                    action: Optional[str] = None) -> Optional[dict]:
    """OOM pre-flight: compare a program's static HBM estimate against
    the device budget BEFORE the first step runs.

    ``action`` defaults to ``FLAGS_memory_preflight`` ('' = off, 'warn',
    'raise'); the limit comes from ``limit_bytes``, else
    ``FLAGS_memory_preflight_limit_mb``, else the device. Returns
    ``{'estimate_bytes', 'limit_bytes', 'fits', 'kind'}`` — or None when
    the check is off or no budget is known (nothing to compare on the
    CPU test backend without an explicit limit)."""
    from ..core.flags import get_flag
    act = action if action is not None else get_flag("memory_preflight")
    if not act:
        return None
    if act not in ("warn", "raise"):
        raise ValueError(f"memory_preflight: unknown action {act!r} "
                         "(expected '', 'warn' or 'raise')")
    limit = _preflight_limit(limit_bytes, device)
    if not limit:
        return None
    progs = ({program.kind: program} if isinstance(program, ProgramMemory)
             else dict(program))
    if not progs:
        return None
    worst_kind, worst = max(progs.items(), key=lambda kv: kv[1].peak_bytes)
    est = worst.peak_bytes
    result = {"estimate_bytes": est, "limit_bytes": int(limit),
              "fits": est <= limit, "kind": worst_kind}
    if est <= limit:
        return result
    msg = (f"memory pre-flight: program {worst_kind!r} needs an estimated "
           f"{fmt_bytes(est)} of HBM "
           f"(args {fmt_bytes(worst.argument_bytes)}, "
           f"outputs {fmt_bytes(worst.output_bytes)}, "
           f"temps {fmt_bytes(worst.temp_bytes)}, "
           f"aliased -{fmt_bytes(worst.alias_bytes)}) but the budget is "
           f"{fmt_bytes(limit)} — this config is expected to OOM. "
           "Shrink the batch, enable recompute/ZeRO, or raise "
           "FLAGS_memory_preflight_limit_mb if the budget is wrong "
           "(docs/OBSERVABILITY.md).")
    try:
        from .metrics import get_registry
        get_registry().counter(
            "memory_preflight_failures_total",
            "programs whose static HBM estimate exceeded the budget"
        ).inc(kind=worst_kind)
    except Exception:
        pass
    if act == "warn":
        warnings.warn(msg, RuntimeWarning, stacklevel=2)
        return result
    raise MemoryBudgetError(msg, estimate_bytes=est, limit_bytes=int(limit))


# ---------------------------------------------------------------------------
# Live-buffer census (jax.live_arrays)
# ---------------------------------------------------------------------------

def _leaf_ids(tree) -> set:
    import jax
    out = set()
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape"):
            out.add(id(leaf))
    return out


def live_buffer_census(train_step=None) -> Dict[str, Dict[str, int]]:
    """Walk ``jax.live_arrays()`` and attribute bytes to where they came
    from: ``params`` / ``optimizer`` / ``buffers`` (matched by identity
    against ``train_step``'s state when one is given), ``activations``
    (floating-point arrays the step does not own — batches, activations,
    user tensors), and ``unattributed`` (everything else: int/bool
    arrays, RNG keys). Returns ``{category: {'bytes', 'count'}}`` plus a
    ``total`` entry.

    This is the live ACTUAL next to the static budget of
    :func:`analyze_compiled` — a growing gap between successive censuses
    is how leaks show up (:class:`LeakMonitor`)."""
    import jax
    import jax.numpy as jnp

    param_ids = opt_ids = buf_ids = frozenset()
    if train_step is not None:
        param_ids = _leaf_ids({**getattr(train_step, "params", {}),
                               **getattr(train_step, "frozen", {})})
        opt_ids = _leaf_ids(getattr(train_step, "opt_state", {}))
        buf_ids = _leaf_ids(getattr(train_step, "buffers", {}))

    cats = {c: {"bytes": 0, "count": 0}
            for c in ("params", "optimizer", "buffers", "activations",
                      "unattributed", "total")}

    def add(cat: str, nbytes: int) -> None:
        cats[cat]["bytes"] += nbytes
        cats[cat]["count"] += 1

    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue
            nbytes = int(arr.nbytes)
        except Exception:
            continue
        i = id(arr)
        if i in param_ids:
            add("params", nbytes)
        elif i in opt_ids:
            add("optimizer", nbytes)
        elif i in buf_ids:
            add("buffers", nbytes)
        elif jnp.issubdtype(arr.dtype, jnp.floating):
            add("activations", nbytes)
        else:
            add("unattributed", nbytes)
        add("total", nbytes)
    return cats


def live_bytes() -> int:
    """Total bytes across all live jax arrays in this process."""
    return live_buffer_census()["total"]["bytes"]


def publish_census(train_step=None, registry=None) \
        -> Dict[str, Dict[str, int]]:
    """Run a census and publish it as ``live_buffer_bytes`` /
    ``live_buffer_count`` gauges labelled by category (rendered by
    ``tools/monitor_report.py --memory``; bench.py calls this before its
    registry dump). Returns the census."""
    census = live_buffer_census(train_step)
    from .metrics import get_registry
    reg = registry if registry is not None else get_registry()
    for cat, c in census.items():
        reg.gauge("live_buffer_bytes",
                  "live jax-array bytes by attribution category "
                  "(monitor.memory census)").set(c["bytes"], category=cat)
        reg.gauge("live_buffer_count",
                  "live jax arrays by attribution category"
                  ).set(c["count"], category=cat)
    return census


class LeakMonitor:
    """Flags monotonic step-over-step growth of live-buffer bytes.

    ::

        leak = LeakMonitor(window=4, tolerance_bytes=1 << 20)
        for step, batch in enumerate(loader):
            train_step(*batch)
            if leak.observe():          # reads live_bytes() by default
                ...                     # warned + counted already

    A leak is suspected when the last ``window`` observations grew
    STRICTLY at every step and the total growth over the window exceeds
    ``tolerance_bytes`` (steady-state training holds live bytes flat:
    donated buffers replace themselves). Suspicion warns
    (RuntimeWarning), bumps ``memory_leak_suspected_total`` in the
    metrics registry, and sets :attr:`suspected`."""

    def __init__(self, window: int = 4, tolerance_bytes: int = 1 << 20,
                 registry=None):
        if window < 2:
            raise ValueError("LeakMonitor: window must be >= 2")
        self.window = int(window)
        self.tolerance_bytes = int(tolerance_bytes)
        self._registry = registry
        self._history: List[int] = []
        self.suspected = 0

    def observe(self, total_bytes: Optional[int] = None,
                step: Optional[int] = None) -> bool:
        """Record one sample (default: :func:`live_bytes` now); True when
        this sample completes a suspicious growth window."""
        v = int(live_bytes() if total_bytes is None else total_bytes)
        self._history.append(v)
        # bounded history: one window is all the detector looks at
        if len(self._history) > self.window + 1:
            del self._history[:-(self.window + 1)]
        h = self._history
        if len(h) < self.window + 1:
            return False
        grew = all(b > a for a, b in zip(h, h[1:]))
        if not grew or h[-1] - h[0] <= self.tolerance_bytes:
            return False
        self.suspected += 1
        growth = h[-1] - h[0]
        at = f" at step {step}" if step is not None else ""
        warnings.warn(
            f"live-buffer leak suspected{at}: live bytes grew "
            f"{fmt_bytes(growth)} over the last {self.window} "
            f"observations ({fmt_bytes(h[0])} -> {fmt_bytes(h[-1])}); "
            "steady-state training should hold live bytes flat — look "
            "for tensors retained across steps (loss history kept as "
            "device arrays, growing python lists of activations)",
            RuntimeWarning, stacklevel=2)
        try:
            from .metrics import get_registry
            reg = self._registry if self._registry is not None \
                else get_registry()
            reg.counter("memory_leak_suspected_total",
                        "LeakMonitor growth-window trips").inc()
        except Exception:
            pass
        return True


# ---------------------------------------------------------------------------
# memory_summary
# ---------------------------------------------------------------------------

def memory_summary(train_step=None, device=None) -> str:
    """Human-readable memory report in the spirit of
    ``paddle.device.cuda.memory_summary``: device actuals (where the
    runtime publishes them), static per-program HBM budgets (from
    ``train_step`` when given, else every program recorded process-wide),
    and the live-buffer census."""
    lines = ["=== paddle_tpu memory summary ==="]

    import jax
    try:
        dev = device if device is not None else jax.devices()[0]
        lines.append(f"device: {dev.device_kind} ({dev.platform})")
    except Exception:
        dev = None
    stats = device_memory_stats(dev)
    if stats is None:
        lines.append("allocator stats: n/a (backend publishes no "
                     "memory_stats — CPU test backend)")
    else:
        lines.append(
            "allocator: in use " + fmt_bytes(stats.get("bytes_in_use"))
            + ", peak " + fmt_bytes(stats.get("peak_bytes_in_use"))
            + ", limit " + fmt_bytes(stats.get("bytes_limit")))

    progs: Dict[str, ProgramMemory]
    if train_step is not None and getattr(train_step, "_program_memory",
                                          None):
        progs = dict(train_step._program_memory)
    else:
        progs = programs()
    if progs:
        lines.append("")
        lines.append("compiled programs (static budget, "
                     "compiled.memory_analysis):")
        hdr = f"  {'kind':<10} {'args':>12} {'outputs':>12} " \
              f"{'temps':>12} {'code':>10} {'peak est.':>12}"
        lines.append(hdr)
        for kind in sorted(progs):
            pm = progs[kind]
            lines.append(
                f"  {kind:<10} {fmt_bytes(pm.argument_bytes):>12} "
                f"{fmt_bytes(pm.output_bytes):>12} "
                f"{fmt_bytes(pm.temp_bytes):>12} "
                f"{fmt_bytes(pm.generated_code_bytes):>10} "
                f"{fmt_bytes(pm.peak_bytes):>12}")

    census = live_buffer_census(train_step)
    lines.append("")
    lines.append("live buffers (jax.live_arrays census):")
    for cat in ("params", "optimizer", "buffers", "activations",
                "unattributed", "total"):
        c = census[cat]
        lines.append(f"  {cat:<14} {fmt_bytes(c['bytes']):>12} "
                     f"in {c['count']} array(s)")
    return "\n".join(lines) + "\n"
