"""Structured tracing: request/step span trees with tail-based anomaly
sampling (ISSUE 11; docs/OBSERVABILITY.md "Structured tracing").

The counters and histograms of :mod:`.metrics` say *how much*; this
module says *where a specific request's (or step's) time went*. The
model is Dapper's — every unit of work is a **trace** (one serving
request, one training step) made of **spans** (trace_id, span_id,
parent link, name, start/end, free-form attributes) — with two
retention rules composed:

- **head sampling**: at trace start a coin flips at
  ``FLAGS_trace_sample`` (default 0.01) — the cheap rate that keeps a
  production engine's trace volume bounded;
- **tail-based anomaly keep**: every trace is *buffered* while open,
  and one that turns out to contain an anomaly — an
  expired/shed/failed/watchdog/chaos/nonfinite event
  (:data:`ANOMALY_REASONS`) — is retained REGARDLESS of the head
  decision. The weird ones are the ones you read; keeping 1% of healthy
  traffic and 100% of incidents is the whole point.

Retained traces live in a bounded ring (``FLAGS_trace_ring``, the
flight-recorder model) and ship three ways:

- :func:`export_perfetto` — one merged Perfetto/chrome-trace JSON:
  trace span trees on per-trace tracks next to the profiler's host
  ``RecordEvent`` timeline (comm events included), openable in
  ``ui.perfetto.dev`` / ``chrome://tracing``;
- the tracer registers a **flight-recorder dump provider**, so a crash
  dump carries the retained *and still-open* traces of the moment it
  died (``monitor_report.py --flight`` readers see them under
  ``"traces"``);
- :meth:`Tracer.dump` writes a standalone JSON rendered by
  ``tools/monitor_report.py --trace`` (span trees with critical-path
  and exclusive-time attribution).

Zero-overhead contract: with ``FLAGS_trace`` off (default),
:func:`start_trace` returns None before allocating anything — the
span-allocation probe :data:`TRACE_STATS` reads 0 and no registry
series are written, pinned by tests/test_trace.py.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

__all__ = [
    "Span", "Trace", "Tracer", "get_tracer", "set_tracer", "enabled",
    "start_trace", "current_trace", "activate", "maybe_span",
    "export_perfetto", "perfetto_doc", "ANOMALY_REASONS", "TRACE_STATS",
    "reset_trace_stats", "load_trace_dump",
]

#: anomaly reasons that force tail-retention of a trace (the serving /
#: training failure modes a post-mortem starts from)
ANOMALY_REASONS = ("expired", "shed", "failed", "watchdog", "chaos",
                   "nonfinite", "health_spike")

#: allocation probe: the zero-overhead pin reads spans_allocated == 0
#: with FLAGS_trace off (tests/test_trace.py)
TRACE_STATS = {"spans_allocated": 0, "traces_started": 0,
               "traces_retained": 0, "traces_dropped": 0,
               "tail_retained": 0}


def reset_trace_stats() -> None:
    for k in TRACE_STATS:
        TRACE_STATS[k] = 0


_trace_seq = itertools.count(1)


def _new_trace_id() -> str:
    return f"{os.getpid():x}-{next(_trace_seq):04x}"


_ctx_seq = itertools.count(1)


def _new_ctx() -> str:
    """Per-Trace context handle, unique ACROSS processes (pid-scoped) —
    the namespace cross-process span references live in. Two processes
    (or two engines in one process) can buffer the same ``trace_id``
    concurrently; their ctx handles never collide, so the fleet merge
    (monitor/fleet.py) can join their spans without id clashes."""
    return f"{os.getpid():x}.{next(_ctx_seq):x}"


class Span:
    """One timed unit of work inside a trace. ``t1`` is None while
    open; ``attrs`` are free-form JSON-safe values."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "attrs")

    def __init__(self, trace_id: str, span_id: int,
                 parent_id: Optional[int], name: str, t0: float,
                 attrs: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.attrs = attrs
        TRACE_STATS["spans_allocated"] += 1

    @property
    def duration(self) -> Optional[float]:
        return None if self.t1 is None else max(0.0, self.t1 - self.t0)

    def set_attrs(self, **attrs) -> None:
        self.attrs.update(attrs)

    def to_dict(self) -> dict:
        return {"span_id": self.span_id, "parent_id": self.parent_id,
                "name": self.name, "t0": self.t0, "t1": self.t1,
                "attrs": {k: _json_safe(v)
                          for k, v in self.attrs.items()}}


def _json_safe(v: Any) -> Any:
    """Non-finite floats serialize as strings ('nan' may be the whole
    point of an anomaly attr) so trace dicts stay valid under
    ``json.dumps(allow_nan=False)`` — the flight-recorder dump's mode."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    try:
        f = float(v)
    except Exception:
        return repr(v)
    return f if math.isfinite(f) else repr(f)


class Trace:
    """One span tree. The root span shares the trace's name and covers
    its whole lifetime; :meth:`span`/:meth:`start_span` children default
    to the root as parent (explicit ``parent=`` nests deeper). Spans may
    open and close at *different* call sites across iterations (the
    serving lifecycle) — handles, not a stack."""

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 head_sampled: bool, t0: float,
                 attrs: Dict[str, Any],
                 process: Optional[str] = None,
                 parent: Optional[str] = None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.head_sampled = head_sampled
        #: unique buffer handle (see :func:`_new_ctx`) — the namespace
        #: qualifying this trace's span ids in cross-process references
        self.ctx = _new_ctx()
        #: which process/replica produced this span tree (one Perfetto
        #: track per distinct process in the merged fleet doc)
        self.process = process
        #: ``"<ctx>/<span_id>"`` of the span (in ANOTHER trace buffer,
        #: usually another process) this tree's root parents under —
        #: the Dapper join the fleet merge resolves
        self.parent_ctx = parent
        #: first anomaly reason seen (None = healthy so far)
        self.anomaly: Optional[str] = None
        self.finished = False
        self._span_seq = itertools.count(1)
        self.root = Span(trace_id, 0, None, name, t0, dict(attrs))
        self.spans: List[Span] = [self.root]

    # -- span surface -------------------------------------------------------
    def start_span(self, name: str, parent: Optional[Span] = None,
                   t: Optional[float] = None, **attrs) -> Span:
        sp = Span(self.trace_id, next(self._span_seq),
                  (parent if parent is not None else self.root).span_id,
                  name, self._tracer.clock() if t is None else t, attrs)
        with self._tracer._lock:
            self.spans.append(sp)
        return sp

    def end_span(self, span: Span, t: Optional[float] = None,
                 **attrs) -> Span:
        if attrs:
            span.attrs.update(attrs)
        if span.t1 is None:
            span.t1 = self._tracer.clock() if t is None else t
        return span

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None,
             **attrs) -> Iterator[Span]:
        sp = self.start_span(name, parent=parent, **attrs)
        try:
            yield sp
        finally:
            self.end_span(sp)

    def event(self, name: str, t: Optional[float] = None,
              **attrs) -> Span:
        """Zero-duration marker span (terminal transitions, preemption
        boundaries)."""
        sp = self.start_span(name, t=t, **attrs)
        sp.t1 = sp.t0
        return sp

    def context_for(self, span: Optional[Span] = None) -> str:
        """The propagation token for ``span`` (default: the root):
        ``"<ctx>/<span_id>"``, globally unambiguous. A downstream
        process opens its trace with ``parent=<token>`` (same
        ``trace_id``) and the fleet merge parents its root there."""
        sp = span if span is not None else self.root
        return f"{self.ctx}/{sp.span_id}"

    def mark_anomaly(self, reason: str, **attrs) -> None:
        """Flag the trace for tail-retention. The FIRST reason sticks
        (it is the one that made the trace weird); later marks only add
        attributes."""
        if self.anomaly is None:
            self.anomaly = str(reason)
        if attrs:
            self.root.attrs.update(attrs)

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        with self._tracer._lock:
            spans = [s.to_dict() for s in self.spans]
        d = {"trace_id": self.trace_id, "name": self.name,
             "ctx": self.ctx,
             "head_sampled": self.head_sampled,
             "anomaly": self.anomaly, "finished": self.finished,
             "spans": spans}
        if self.process is not None:
            d["process"] = self.process
        if self.parent_ctx is not None:
            d["parent_ctx"] = self.parent_ctx
        return d


class Tracer:
    """Process-global trace buffer: open traces + a bounded ring of
    retained (finished) ones."""

    def __init__(self, capacity: Optional[int] = None,
                 clock=time.perf_counter, seed: Optional[int] = None):
        if capacity is None:
            try:
                from ..core.flags import get_flag
                capacity = int(get_flag("trace_ring"))
            except Exception:
                capacity = 64
        self.capacity = max(1, int(capacity))
        self.clock = clock
        self._lock = threading.RLock()
        self._live: Dict[str, Trace] = {}
        self._retained: List[Trace] = []
        self._rng = random.Random(seed)

    def _sample_rate(self) -> float:
        try:
            from ..core.flags import get_flag
            return float(get_flag("trace_sample"))
        except Exception:
            return 0.0

    # -- lifecycle ----------------------------------------------------------
    def start_trace(self, name: str, trace_id: Optional[str] = None,
                    sample: Optional[bool] = None, t: Optional[float]
                    = None, process: Optional[str] = None,
                    parent: Optional[str] = None, **attrs) -> Trace:
        """Open a trace. ``trace_id`` resumes an identity (drain/resume
        and the fleet router hand the id across engines); ``sample``
        overrides the head coin (tests, resumed traces that were
        already being kept); ``process`` labels the producing
        process/replica (one Perfetto track per process in the merged
        fleet doc); ``parent`` is a :meth:`Trace.context_for` token the
        new tree's root parents under — the cross-process Dapper link.
        The live table keys on the per-Trace ``ctx`` handle, so a
        router trace and an in-process replica trace may buffer the
        SAME trace_id concurrently without evicting each other."""
        if sample is None:
            rate = self._sample_rate()
            sample = (rate >= 1.0
                      or (rate > 0.0 and self._rng.random() < rate))
        tr = Trace(self, name,
                   trace_id if trace_id else _new_trace_id(),
                   bool(sample), self.clock() if t is None else t,
                   attrs, process=process, parent=parent)
        with self._lock:
            self._live[tr.ctx] = tr
            TRACE_STATS["traces_started"] += 1
        return tr

    def finish_trace(self, trace: Trace, t: Optional[float] = None) \
            -> bool:
        """Close the root span and apply the retention decision:
        head-sampled OR anomalous ⇒ ring; else dropped. Returns whether
        the trace was retained. Idempotent."""
        with self._lock:
            if trace.finished:
                return trace in self._retained
            trace.finished = True
            self._live.pop(trace.ctx, None)
            trace.end_span(trace.root, t=t)
            keep = trace.head_sampled or trace.anomaly is not None
            if keep:
                if trace.anomaly is not None and not trace.head_sampled:
                    TRACE_STATS["tail_retained"] += 1
                TRACE_STATS["traces_retained"] += 1
                self._retained.append(trace)
                if len(self._retained) > self.capacity:
                    del self._retained[:len(self._retained)
                                       - self.capacity]
            else:
                TRACE_STATS["traces_dropped"] += 1
            return keep

    # -- reads --------------------------------------------------------------
    def retained(self) -> List[Trace]:
        with self._lock:
            return list(self._retained)

    def live(self) -> List[Trace]:
        with self._lock:
            return list(self._live.values())

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._retained.clear()

    # -- export -------------------------------------------------------------
    def snapshot(self, include_live: bool = True) -> List[dict]:
        return [t.to_dict() for t in self.retained()] + \
            ([t.to_dict() for t in self.live()] if include_live else [])

    def dump(self, path: str, include_live: bool = True) -> str:
        """Standalone trace dump (atomic rename), rendered by
        ``tools/monitor_report.py --trace <path>``."""
        doc = {"format": 1, "dumped_at": time.time(),
               "traces": self.snapshot(include_live=include_live)}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path


def load_trace_dump(path: str) -> List[dict]:
    """Parse a :meth:`Tracer.dump` file (or a flight-recorder dump that
    carries a ``traces`` section) into a list of trace dicts."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    return list(doc.get("traces") or [])


# ---------------------------------------------------------------------------
# Process-global tracer + flag gate
# ---------------------------------------------------------------------------

_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer (created on first use; registers the
    flight-recorder dump provider so crash dumps carry traces)."""
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
            _register_flight_provider()
        return _tracer


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Swap the process-global tracer (tests); returns the old one."""
    global _tracer
    with _tracer_lock:
        old, _tracer = _tracer, tracer
        return old


def _register_flight_provider() -> None:
    try:
        from . import flight_recorder as _flight
        _flight.register_dump_provider("traces", _flight_traces)
    except Exception:
        pass


def _flight_traces() -> List[dict]:
    """Flight-recorder dump provider: retained + in-flight traces, so a
    crash ships the span trees of whatever it was serving."""
    t = _tracer
    return t.snapshot(include_live=True) if t is not None else []


def enabled() -> bool:
    """True when ``FLAGS_trace`` is on — the ONE gate every hot path
    reads before touching the tracer."""
    from ..core.flags import get_flag
    return bool(get_flag("trace"))


def start_trace(name: str, **kw) -> Optional[Trace]:
    """Flag-gated entry point: None (no allocation at all) when
    ``FLAGS_trace`` is off."""
    if not enabled():
        return None
    return get_tracer().start_trace(name, **kw)


# -- current-trace context (training step spans attach through this) --------

_current = threading.local()


def current_trace() -> Optional[Trace]:
    stack = getattr(_current, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def activate(trace: Optional[Trace]) -> Iterator[Optional[Trace]]:
    """Make ``trace`` the thread's current trace for the with-block so
    nested instrumentation (eager collectives, checkpoint commits) can
    attach child spans via :func:`maybe_span`. None = no-op."""
    if trace is None:
        yield None
        return
    stack = getattr(_current, "stack", None)
    if stack is None:
        stack = _current.stack = []
    stack.append(trace)
    try:
        yield trace
    finally:
        stack.pop()


@contextlib.contextmanager
def maybe_span(name: str, **attrs) -> Iterator[Optional[Span]]:
    """Open ``name`` under the thread's current trace, or do nothing
    when there is none (the cheap seam for instrumentation that cannot
    know whether a trace is active — collective dispatches, checkpoint
    commits). Never raises out of the guard."""
    tr = current_trace()
    if tr is None:
        yield None
        return
    sp = tr.start_span(name, **attrs)
    try:
        yield sp
    finally:
        tr.end_span(sp)


# ---------------------------------------------------------------------------
# Perfetto / chrome-trace export
# ---------------------------------------------------------------------------


def perfetto_doc(traces: Optional[List[dict]] = None,
                 include_host_timeline: bool = True) -> dict:
    """The merged Perfetto/chrome-trace document as a dict — what
    :func:`export_perfetto` writes. Factored out so the admin server's
    ``/debug/trace?format=perfetto`` serves it straight from memory.

    Track model (ISSUE 18): one Perfetto *process* (pid) per distinct
    producing process label — a trace doc's ``process`` field, or a
    per-span ``process`` key in a fleet-merged doc — and inside each
    process ONE track (tid) per ``trace_id``. Docs without a process
    label all land on the classic ``paddle_tpu.trace`` pid, and
    distinct trace_ids get distinct tids, so single-process exports
    render exactly as before; a merged fleet trace renders as the
    router process plus one process per replica, each showing its own
    slice of the same request side by side."""
    if traces is None:
        traces = get_tracer().snapshot(include_live=True)
    events: List[dict] = []
    meta: List[dict] = []
    pids: Dict[Optional[str], int] = {}
    tids: Dict[tuple, int] = {}

    def _pid(proc: Optional[str]) -> int:
        pid = pids.get(proc)
        if pid is None:
            pid = pids[proc] = len(pids) + 1
            label = ("paddle_tpu.trace" if proc is None
                     else f"paddle_tpu.trace:{proc}")
            meta.append({"ph": "M", "name": "process_name", "pid": pid,
                         "args": {"name": label}})
        return pid

    def _tid(pid: int, tdoc: dict) -> int:
        key = (pid, tdoc.get("trace_id"))
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = 1 + sum(1 for p, _ in tids if p == pid)
            label = (f"{tdoc.get('name', 'trace')} "
                     f"{tdoc.get('trace_id', '')}")
            if tdoc.get("anomaly"):
                label += f" [ANOMALY:{tdoc['anomaly']}]"
            meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                         "tid": tid, "args": {"name": label}})
        return tid

    for tdoc in traces:
        doc_proc = tdoc.get("process")
        for s in tdoc.get("spans") or []:
            t0 = s.get("t0")
            if t0 is None:
                continue
            pid = _pid(s.get("process", doc_proc))
            tid = _tid(pid, tdoc)
            t1 = s.get("t1")
            dur = 0.0 if t1 is None else max(0.0, float(t1) - float(t0))
            args = dict(s.get("attrs") or {})
            args["trace_id"] = tdoc.get("trace_id")
            args["span_id"] = s.get("span_id")
            if s.get("parent_id") is not None:
                args["parent_id"] = s.get("parent_id")
            events.append({"name": s.get("name", "?"), "ph": "X",
                           "ts": float(t0) * 1e6, "dur": dur * 1e6,
                           "pid": pid, "tid": tid, "cat": "trace",
                           "args": args})
    if include_host_timeline:
        try:
            from ..profiler import _timeline
            meta.append({"ph": "M", "name": "process_name", "pid": 0,
                         "args": {"name": "host (profiler)"}})
            for name, ts, dur, tid in list(_timeline):
                events.append({"name": name, "ph": "X", "ts": ts,
                               "dur": dur, "pid": 0,
                               "tid": tid % 100000, "cat": "host"})
        except Exception:
            pass
    events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"]))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def export_perfetto(path: str, traces: Optional[List[dict]] = None,
                    include_host_timeline: bool = True) -> str:
    """Write ONE merged Perfetto/chrome-trace JSON: every retained (and
    open) trace's span tree on its own track, plus the profiler's host
    ``RecordEvent`` timeline (step spans, ``comm::<op>`` events, eager
    op dispatches) on per-thread tracks — the unified timeline the
    reference's device_tracer assembled from CUPTI + host events.

    Timestamps are microseconds in the host ``perf_counter`` domain
    (both sources share it), emitted sorted per track so the file loads
    with monotonic track clocks. Openable in ui.perfetto.dev or
    chrome://tracing."""
    doc = perfetto_doc(traces, include_host_timeline)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path
