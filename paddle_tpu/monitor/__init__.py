"""paddle_tpu.monitor — unified training telemetry.

Time-domain pillars (ISSUE 3; see docs/OBSERVABILITY.md):

1. a structured **metrics registry** (:mod:`.metrics`): thread-safe
   Counter/Gauge/Histogram with labels, Prometheus text + append-only
   JSONL export, a process-global default registry plus
   :func:`scoped_registry` for tests;
2. **step-time instrumentation** in :class:`~paddle_tpu.jit.to_static.
   TrainStep` — ``TrainStep.stats()`` snapshots compiles/recompiles,
   eager-cache hit rates and (under ``FLAGS_monitor``) per-step
   wall/dispatch timings streamed into the registry;
3. **collective tracing** (:mod:`paddle_tpu.distributed.collective`):
   every eager collective records op/group/bytes/latency counters and a
   host-timeline RecordEvent;
4. the **NaN/Inf watchdog** (:mod:`.numerics`): eager post-step checks
   that name the first offending parameter/gradient and step index,
   AMP-GradScaler aware.

Memory/cost/forensics pillars (ISSUE 4, the space-domain counterpart):

5. **HBM memory accounting** (:mod:`.memory`): static per-program
   budgets from ``compiled.memory_analysis()`` (surfaced per program
   kind in ``TrainStep.stats()['programs']``), the flag-gated OOM
   pre-flight check (``FLAGS_memory_preflight``), a live-buffer census
   over ``jax.live_arrays()`` with :class:`~.memory.LeakMonitor`
   growth detection, and :func:`~.memory.memory_summary`;
6. **per-program cost attribution** — FLOPs/bytes/arithmetic intensity
   from ``lowered.cost_analysis()`` via :mod:`paddle_tpu.cost_model`
   (one shared source of truth with ``CostModel.profile_measure`` and
   bench.py's MFU math);
7. the **crash flight recorder** (:mod:`.flight_recorder`): a bounded
   ring of recent step records + events + an environment fingerprint,
   dumped to JSON on unhandled exceptions, NaN-watchdog trips, or
   explicit ``dump()``, with faulthandler wiring for hard crashes.

Where-did-the-time-go pillars (ISSUE 11):

8. **structured tracing** (:mod:`.trace`): per-request / per-step span
   trees with trace ids, head-rate + tail-based anomaly sampling
   (``FLAGS_trace`` / ``FLAGS_trace_sample``), a unified Perfetto
   export merged with the profiler host timeline, histogram exemplars,
   and trace attachment to flight-recorder dumps;
9. **SLO burn rate** (:mod:`.slo`): multi-window error-budget burn
   tracking over serving outcomes with SRE-workbook multiwindow alert
   arithmetic.

Live telemetry plane (ISSUE 14 — the pull-while-running half):

10. the **embedded admin server** (:mod:`.server`,
    ``FLAGS_monitor_port``): ``/metrics`` (Prometheus text with
    exemplars), ``/healthz`` + ``/readyz`` wired to the serving
    engine's state machine, ``/statusz``, and on-demand
    ``/debug/{flight,trace,profile}`` capture from the LIVE process;
11. the **timeseries ring** (:mod:`.timeseries`): bounded per-scrape
    registry snapshots turning cumulative counters into rates
    (``tools/monitor_top.py``), plus **multi-host aggregation**
    (``MetricsRegistry.merge`` / ``tools/aggregate_metrics.py``).

Fleet observability plane (ISSUE 18 — one pane for many processes):

12. the **fleet federator** (:mod:`.fleet`, ``FLAGS_fleet_monitor_*``):
    a scrape loop federating every replica's ``/metrics`` page (plus
    the router's registry) into ONE host-labelled fleet registry with
    its own admin plane, cross-process trace merging
    (:func:`~.fleet.merge_fleet_traces` joins the router's
    ``fleet.request`` tree with each replica's ``serve.request`` tree
    under one trace_id), fleet SLO burn over the federated counters,
    and anomaly-triggered, rate-limited incident bundles.

Training goodput & model health (ISSUE 19 — the training-side plane):

13. the **goodput ledger** (:mod:`.goodput`, ``FLAGS_train_goodput``):
    every second of trainer wall-clock attributed to ONE exclusive
    bucket (productive_dispatch / compile / data_wait /
    checkpoint_stall / nonfinite_rollback / restart_gap / host_other),
    persisted across SIGTERM→resume through the CheckpointManager
    sidecar, published as ``train_goodput_pct`` +
    ``train_badput_seconds_total{bucket}`` with a /statusz section and
    ``data_wait`` spans on the step trace;
14. **per-layer model health** (``FLAGS_train_health_every``): f32
    grad-norm / param-norm / update-ratio side-outputs compiled into
    the step program (scan layouts included), ``train_layer_*`` gauges,
    and the :class:`~.goodput.LayerHealthMonitor` EWMA spike detector
    that tail-marks step traces and feeds flight-recorder dumps.

The registry is always importable and writable; the HOT paths only write
to it when ``FLAGS_monitor`` is set (zero-overhead default, pinned by
the write_count guard in tests/test_monitor.py; the flight recorder has
the same contract via ``FLAGS_flight_recorder`` and its
``record_count`` probe).
"""

from . import (fleet, flight_recorder, goodput, memory,  # noqa: F401
               slo, timeseries, trace)
from .goodput import GoodputLedger, LayerHealthMonitor  # noqa: F401
from .flight_recorder import (FlightRecorder,  # noqa: F401
                              get_flight_recorder, set_flight_recorder)
from .memory import (LeakMonitor, MemoryBudgetError,  # noqa: F401
                     ProgramMemory, live_buffer_census, memory_summary,
                     preflight_check)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      get_registry, lint_exposition, load_jsonl,
                      load_registry_jsonl, scoped_registry)
from .timeseries import TimeseriesRing  # noqa: F401
from .numerics import (NaNWatchdog, NonFiniteError, all_finite,  # noqa: F401
                       check_numerics, first_nonfinite, nonfinite_entries)
from .slo import SLOTracker  # noqa: F401
from .trace import (Span, Trace, Tracer, export_perfetto,  # noqa: F401
                    get_tracer, set_tracer, start_trace)
from .fleet import FleetFederator, merge_fleet_traces  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "scoped_registry", "load_jsonl", "load_registry_jsonl",
    "lint_exposition", "TimeseriesRing",
    "NaNWatchdog", "NonFiniteError", "all_finite", "check_numerics",
    "first_nonfinite", "nonfinite_entries",
    "ProgramMemory", "MemoryBudgetError", "LeakMonitor",
    "live_buffer_census", "memory_summary", "preflight_check",
    "FlightRecorder", "get_flight_recorder", "set_flight_recorder",
    "enabled",
    "Span", "Trace", "Tracer", "get_tracer", "set_tracer",
    "start_trace", "export_perfetto", "SLOTracker",
    "FleetFederator", "merge_fleet_traces",
    "GoodputLedger", "LayerHealthMonitor",
]


def enabled() -> bool:
    """True when ``FLAGS_monitor`` is set — hot paths consult this before
    writing per-step samples into the registry."""
    from ..core.flags import get_flag
    return bool(get_flag("monitor"))
